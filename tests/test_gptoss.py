"""GPT-OSS: HF logit parity, sinks semantics, and end-to-end serving.

Attention sinks, qkv/o biases, yarn rope, alternating sliding windows,
and the clamped-GLU MoE all in play. Reference analog: the GPT-OSS
models of the engines the reference delegates to (SURVEY §2.4)."""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.models import gptoss, resolve
from dynamo_tpu.models.loader import load_checkpoint_params

from fixtures import make_model_dir

TINY = dict(
    vocab_size=256,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=4,       # two sliding + two full layers
    num_attention_heads=4,
    num_key_value_heads=2,
    head_dim=8,
    num_local_experts=4,
    num_experts_per_tok=2,
    max_position_embeddings=128,
    rms_norm_eps=1e-6,
    rope_theta=10000.0,
    sliding_window=4,          # bites inside the test prompt
    tie_word_embeddings=False,
)

PROMPT = [2, 17, 43, 99, 7, 3, 250, 12, 5, 77, 140, 9]


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    import torch
    from transformers import GptOssConfig, GptOssForCausalLM

    d = make_model_dir(tmp_path_factory.mktemp("gptoss"), name="tiny-gptoss")
    cfg = GptOssConfig(**TINY)
    torch.manual_seed(0)
    model = GptOssForCausalLM(cfg)
    # empty-initialized params (sinks, biases) get real values so the
    # sink/bias paths are actually exercised
    with torch.no_grad():
        for name, p in model.named_parameters():
            if "sinks" in name or "bias" in name:
                p.normal_(0.0, 0.5)
    model.save_pretrained(d, safe_serialization=True)
    with open(os.path.join(d, "config.json")) as f:
        c = json.load(f)
    c["eos_token_id"] = 1
    c["bos_token_id"] = 2
    with open(os.path.join(d, "config.json"), "w") as f:
        json.dump(c, f)
    return d


@pytest.fixture(scope="module")
def hf_out(model_dir):
    import torch
    from transformers import GptOssForCausalLM

    model = GptOssForCausalLM.from_pretrained(
        model_dir, torch_dtype=torch.float32, attn_implementation="eager"
    )
    model.eval()
    with torch.no_grad():
        logits = model(torch.tensor([PROMPT])).logits[0].numpy()
        gen = model.generate(
            torch.tensor([PROMPT]), max_new_tokens=8, do_sample=False,
        )[0][len(PROMPT):].tolist()
    return logits, gen


def test_resolve_and_config(model_dir):
    cfg = ModelConfig.from_model_dir(model_dir)
    assert cfg.model_family == "gptoss"
    assert cfg.num_experts == 4 and cfg.attention_bias
    assert cfg.sliding_window == 4
    assert cfg.rope_scaling and cfg.rope_scaling.get("rope_type") == "yarn"
    assert resolve(cfg) is gptoss


def test_gptoss_prefill_logits_match_hf(model_dir, hf_out):
    hf_logits, _ = hf_out
    cfg = ModelConfig.from_model_dir(model_dir)
    cfg.attention_impl = "xla"
    cfg.moe_capacity_factor = 8.0
    params = load_checkpoint_params(model_dir, cfg, gptoss, jnp.float32)
    for key in ("sinks", "bo", "router_bias", "b_gate_up", "b_down"):
        assert key in params["layers"], key
    s = len(PROMPT)
    k, v = gptoss.init_kv_cache(cfg, 16, 8, jnp.float32)
    tokens = jnp.asarray([PROMPT], jnp.int32)
    positions = jnp.arange(s, dtype=jnp.int32)[None]
    bt = jnp.arange(4, dtype=jnp.int32)[None]
    logits, _ = gptoss.forward(
        params, cfg, tokens, positions, (k, v), bt, positions,
        jnp.asarray([s], jnp.int32),
    )
    np.testing.assert_allclose(
        np.asarray(logits[0]), hf_logits, rtol=2e-4, atol=2e-4
    )


@pytest.mark.asyncio
async def test_gptoss_engine_greedy_matches_hf_generate(model_dir, hf_out):
    from dynamo_tpu.engine.serving import JaxServingEngine
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest, SamplingOptions, StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    _, hf_gen = hf_out
    mdc = ModelDeploymentCard.from_local_path(model_dir)
    mcfg = ModelConfig.from_model_dir(model_dir)
    mcfg.attention_impl = "xla"
    mcfg.moe_capacity_factor = 8.0
    econfig = EngineConfig(
        model=mcfg, max_batch_size=2, max_model_len=64, kv_block_size=8,
        num_kv_blocks=32, dtype="float32",
    )
    engine = await JaxServingEngine.create(
        mdc, engine_config=econfig, warmup=False)
    req = PreprocessedRequest(
        token_ids=PROMPT,
        stop_conditions=StopConditions(max_tokens=8, ignore_eos=True),
        sampling_options=SamplingOptions(temperature=0.0),
    )
    toks = []
    async for out in engine.generate(Context(req)):
        toks.extend(out["token_ids"])
    await engine.close()
    assert toks == hf_gen


def test_nonalternating_layer_types_rejected():
    with pytest.raises(NotImplementedError, match="alternate"):
        ModelConfig.from_hf_config(
            {**TINY, "architectures": ["GptOssForCausalLM"],
             "layer_types": ["full_attention"] * 4}
        )


def test_gptoss_int8_logits_close(model_dir):
    """int8 weight-only serving quantizes the attention projections and
    both expert stacks (incl. the fused interleaved gate_up — per-out-
    channel scales are interleaving-safe); logits stay close to fp32."""
    from dynamo_tpu.models.quant import QuantizedWeight, quantize_params

    cfg = ModelConfig.from_model_dir(model_dir)
    cfg.attention_impl = "xla"
    cfg.moe_capacity_factor = 8.0
    params = load_checkpoint_params(model_dir, cfg, gptoss, jnp.float32)
    qparams = quantize_params(params)
    assert isinstance(qparams["layers"]["w_gate_up"], QuantizedWeight)
    assert isinstance(qparams["layers"]["w_down"], QuantizedWeight)

    s = len(PROMPT)
    tokens = jnp.asarray([PROMPT], jnp.int32)
    positions = jnp.arange(s, dtype=jnp.int32)[None]
    bt = jnp.arange(4, dtype=jnp.int32)[None]
    outs = []
    for p in (params, qparams):
        k, v = gptoss.init_kv_cache(cfg, 16, 8, jnp.float32)
        logits, _ = gptoss.forward(
            p, cfg, tokens, positions, (k, v), bt, positions,
            jnp.asarray([s], jnp.int32),
        )
        outs.append(np.asarray(logits[0]))
    # int8 error bound: loose but meaningful (random tiny model)
    np.testing.assert_allclose(outs[1], outs[0], rtol=0.2, atol=0.2)


def test_mxfp4_checkpoint_dequantizes_at_load(model_dir, tmp_path):
    """The canonical GPT-OSS releases ship expert weights as MXFP4
    (*_blocks + *_scales). Pack FP4-representable weights, rewrite the
    tiny checkpoint, and the loader must produce the exact values."""
    import glob as globmod
    import shutil

    from safetensors import numpy as st_np

    from dynamo_tpu.models.loader import _FP4_VALUES, load_gptoss_params

    d = str(tmp_path / "mx")
    shutil.copytree(model_dir, d)

    rng = np.random.default_rng(0)

    def pack(out_dim, in_dim, e):
        """Random FP4-grid values x power-of-two block scales, plus the
        packed (blocks, scales) encoding of the same tensor."""
        g = in_dim // 32
        nibbles = rng.integers(0, 16, (e, out_dim, g, 32), dtype=np.uint8)
        scales = rng.integers(125, 130, (e, out_dim, g), dtype=np.uint8)
        vals = _FP4_VALUES[nibbles] * np.exp2(
            scales.astype(np.int32) - 127
        )[..., None].astype(np.float32)
        dense_w = vals.reshape(e, out_dim, in_dim)
        blocks = (nibbles[..., 0::2] | (nibbles[..., 1::2] << 4)).astype(np.uint8)
        return dense_w, blocks, scales

    cfg = ModelConfig.from_model_dir(d)
    e, dm, inter = cfg.num_experts, cfg.hidden_size, cfg.intermediate_size
    expected = {}
    [st_file] = globmod.glob(os.path.join(d, "*.safetensors"))
    tensors = dict(st_np.load_file(st_file))
    for li in range(cfg.num_layers):
        gu_w, gu_b, gu_s = pack(2 * inter, dm, e)
        dn_w, dn_b, dn_s = pack(dm, inter, e)
        base = f"model.layers.{li}.mlp.experts."
        for proj in ("gate_up_proj", "down_proj"):
            tensors.pop(base + proj, None)
        tensors[base + "gate_up_proj_blocks"] = gu_b
        tensors[base + "gate_up_proj_scales"] = gu_s
        tensors[base + "down_proj_blocks"] = dn_b
        tensors[base + "down_proj_scales"] = dn_s
        # engine layout [E, in, out]
        expected[li] = (gu_w.transpose(0, 2, 1), dn_w.transpose(0, 2, 1))
    st_np.save_file(tensors, st_file)

    params = load_gptoss_params(d, cfg, jnp.float32)
    for li, (gu, dn) in expected.items():
        np.testing.assert_array_equal(
            np.asarray(params["layers"]["w_gate_up"][li]), gu
        )
        np.testing.assert_array_equal(
            np.asarray(params["layers"]["w_down"][li]), dn
        )


def test_incomplete_checkpoint_fails_loudly(model_dir, tmp_path):
    """A checkpoint whose expert tensors use an unrecognized naming must
    fail with the loader's diagnostic, not a KeyError mid-trace."""
    import glob as globmod
    import shutil

    from safetensors import numpy as st_np

    from dynamo_tpu.models.loader import load_gptoss_params

    d = str(tmp_path / "broken")
    shutil.copytree(model_dir, d)
    [st_file] = globmod.glob(os.path.join(d, "*.safetensors"))
    tensors = dict(st_np.load_file(st_file))
    renamed = {
        k.replace("mlp.experts.gate_up_proj", "mlp.experts.mystery")
        if "gate_up_proj" in k else k: v
        for k, v in tensors.items()
    }
    st_np.save_file(renamed, st_file)
    cfg = ModelConfig.from_model_dir(d)
    with pytest.raises(ValueError, match="missing.*w_gate_up"):
        load_gptoss_params(d, cfg, jnp.float32)


def test_gptoss_pallas_kernels_match_xla(model_dir, monkeypatch):
    """The sinks+window kernel variants serve GPT-OSS's full forward —
    parity vs the XLA path for prefill AND a decode step."""
    monkeypatch.setenv("DYN_PALLAS_INTERPRET", "1")
    cfg_x = ModelConfig.from_model_dir(model_dir)
    cfg_x.attention_impl = "xla"
    cfg_x.moe_capacity_factor = 8.0
    cfg_p = ModelConfig.from_model_dir(model_dir)
    cfg_p.attention_impl = "pallas"
    cfg_p.moe_capacity_factor = 8.0
    params = load_checkpoint_params(model_dir, cfg_x, gptoss, jnp.float32)

    s = len(PROMPT)
    tokens = jnp.asarray([PROMPT], jnp.int32)
    positions = jnp.arange(s, dtype=jnp.int32)[None]
    bt = jnp.arange(4, dtype=jnp.int32)[None]
    ctx = jnp.asarray([s], jnp.int32)

    outs = {}
    for name, cfg in (("xla", cfg_x), ("pallas", cfg_p)):
        k, v = gptoss.init_kv_cache(cfg, 16, 8, jnp.float32)
        logits, (k, v) = gptoss.forward(
            params, cfg, tokens, positions, (k, v), bt, positions, ctx
        )
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)[:, None]
        dlogits, _ = gptoss.forward(
            params, cfg, nxt, jnp.asarray([[s]], jnp.int32), (k, v), bt,
            jnp.asarray([[s]], jnp.int32), jnp.asarray([s + 1], jnp.int32),
        )
        outs[name] = (np.asarray(logits), np.asarray(dlogits))

    np.testing.assert_allclose(
        outs["pallas"][0], outs["xla"][0], rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(
        outs["pallas"][1], outs["xla"][1], rtol=2e-4, atol=2e-4
    )


def _gptoss_run_step(model_dir, params, mcfg, pp, ep, tp, seed):
    from dynamo_tpu.engine.model_runner import ModelRunner

    runner = ModelRunner(EngineConfig(
        model=mcfg, max_batch_size=4, max_model_len=64, kv_block_size=8,
        num_kv_blocks=64, dtype="float32", pp_size=pp, ep_size=ep,
        tp_size=tp, prefill_buckets=[16],
    ), params=params)
    b, s, bs = 4, 8, 8
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, mcfg.vocab_size, (b, s)).astype(np.int32)
    positions = np.tile(np.arange(s, dtype=np.int32), (b, 1))
    w = runner.config.blocks_per_seq
    btab = np.zeros((b, w), np.int32)
    for i in range(b):
        btab[i, 0] = i
    slots = btab[:, :1] * bs + positions
    out, *_ = runner.step(
        tokens, positions, btab, slots, np.full(b, s, np.int32),
        np.full(b, s - 1, np.int32), np.zeros(b, np.float32),
        np.zeros(b, np.int32), np.ones(b, np.float32),
        jax.random.PRNGKey(seed + 1),
    )
    return np.asarray(out)


@pytest.fixture(scope="module")
def pp_reference(model_dir):
    """Unstaged single-device greedy step, computed once for every
    staged-topology parametrization."""
    mcfg = ModelConfig.from_model_dir(model_dir)
    mcfg.attention_impl = "xla"
    params = load_checkpoint_params(model_dir, mcfg, gptoss, jnp.float32)
    ref = _gptoss_run_step(model_dir, params, mcfg, 1, 1, 1, seed=21)
    return params, mcfg, ref


@pytest.mark.parametrize("pp,ep,tp", [(2, 2, 1), (2, 2, 2)])
def test_gptoss_pp_matches_single_stage(model_dir, pp_reference, pp, ep, tp):
    """GPT-OSS staged over pp x ep (x tp): sinks, biases, GLOBAL-layer
    window alternation, local-expert slicing + psum — and at tp>1 the
    pair-preserving 2I expert chunks, 1/tp-scaled bo/b_down, and
    tp-sharded sinks — must reproduce the unstaged greedy step."""
    params, mcfg, ref = pp_reference
    got = _gptoss_run_step(model_dir, params, mcfg, pp, ep, tp, seed=21)
    np.testing.assert_array_equal(got, ref)


def test_gptoss_pp_tp_indivisible_width_rejected():
    """Heads and kv-heads divide tp here, so ONLY the expert-width
    guard can fire: intermediate_size 45 % tp 2 != 0."""
    from dynamo_tpu.engine.model_runner import ModelRunner

    mcfg = ModelConfig(
        vocab_size=128, hidden_size=32, intermediate_size=45, num_layers=4,
        num_heads=4, num_kv_heads=2, head_dim=8, model_family="gptoss",
        num_experts=4, num_experts_per_tok=2, sliding_window=4,
        attention_bias=True,
    )
    with pytest.raises(ValueError, match="intermediate_size 45"):
        ModelRunner(EngineConfig(
            model=mcfg, max_batch_size=4, max_model_len=32, kv_block_size=8,
            num_kv_blocks=16, dtype="float32", pp_size=2, tp_size=2,
            allow_random_weights=True,
        ))
