"""GGUF tensor data: dequantization vs scalar references, loader e2e.

The vectorized dequantizers (llm/gguf_tensors.py) are checked against
independent straight-from-the-spec scalar loops over random block bytes;
the .gguf weight loader is checked by exporting a tiny HF checkpoint to
GGUF (llama.cpp naming + q/k permute, as the public converter does) and
asserting the loaded param pytree matches the safetensors loader's.
"""

import json
import os
import struct

import numpy as np
import pytest

import jax.numpy as jnp

from dynamo_tpu.engine.config import ModelConfig
from dynamo_tpu.llm.gguf import read_gguf
from dynamo_tpu.llm.gguf_tensors import (
    _DEQUANT,
    dequantize,
    iter_gguf_tensors,
    tensor_nbytes,
)
from dynamo_tpu.llm.gguf import GgufTensorInfo
from test_gguf import T_ARRAY, T_FLOAT32, T_STRING, T_UINT32, _kv, _s

rng = np.random.default_rng(7)


def _f16s(b, off):
    return np.frombuffer(b, "<f2", count=1, offset=off)[0].astype(np.float32)


# ---- scalar references (independent re-reading of the ggml spec) ----

def ref_q8_0(b, n):
    out = []
    for blk in range(len(b) // 34):
        o = blk * 34
        d = _f16s(b, o)
        q = np.frombuffer(b, np.int8, count=32, offset=o + 2)
        out.extend(float(d) * float(x) for x in q)
    return np.array(out[:n], np.float32)


def ref_q4_0(b, n):
    out = []
    for blk in range(len(b) // 18):
        o = blk * 18
        d = _f16s(b, o)
        qs = b[o + 2 : o + 18]
        vals = [0.0] * 32
        for j in range(16):
            vals[j] = float(d) * ((qs[j] & 0x0F) - 8)
            vals[j + 16] = float(d) * ((qs[j] >> 4) - 8)
        out.extend(vals)
    return np.array(out[:n], np.float32)


def ref_q4_1(b, n):
    out = []
    for blk in range(len(b) // 20):
        o = blk * 20
        d, m = _f16s(b, o), _f16s(b, o + 2)
        qs = b[o + 4 : o + 20]
        vals = [0.0] * 32
        for j in range(16):
            vals[j] = float(d) * (qs[j] & 0x0F) + float(m)
            vals[j + 16] = float(d) * (qs[j] >> 4) + float(m)
        out.extend(vals)
    return np.array(out[:n], np.float32)


def ref_q5_0(b, n):
    out = []
    for blk in range(len(b) // 22):
        o = blk * 22
        d = _f16s(b, o)
        qh = struct.unpack_from("<I", b, o + 2)[0]
        qs = b[o + 6 : o + 22]
        vals = [0.0] * 32
        for j in range(16):
            x0 = (qs[j] & 0x0F) | (((qh >> j) & 1) << 4)
            x1 = (qs[j] >> 4) | (((qh >> (j + 16)) & 1) << 4)
            vals[j] = float(d) * (x0 - 16)
            vals[j + 16] = float(d) * (x1 - 16)
        out.extend(vals)
    return np.array(out[:n], np.float32)


def ref_q5_1(b, n):
    out = []
    for blk in range(len(b) // 24):
        o = blk * 24
        d, m = _f16s(b, o), _f16s(b, o + 2)
        qh = struct.unpack_from("<I", b, o + 4)[0]
        qs = b[o + 8 : o + 24]
        vals = [0.0] * 32
        for j in range(16):
            x0 = (qs[j] & 0x0F) | (((qh >> j) & 1) << 4)
            x1 = (qs[j] >> 4) | (((qh >> (j + 16)) & 1) << 4)
            vals[j] = float(d) * x0 + float(m)
            vals[j + 16] = float(d) * x1 + float(m)
        out.extend(vals)
    return np.array(out[:n], np.float32)


def _scale_min_k4(scales, j):
    if j < 4:
        return scales[j] & 63, scales[j + 4] & 63
    sc = (scales[j + 4] & 0x0F) | ((scales[j - 4] >> 6) << 4)
    mn = (scales[j + 4] >> 4) | ((scales[j] >> 6) << 4)
    return sc, mn


def ref_q4_k(b, n):
    bs = 2 + 2 + 12 + 128
    out = []
    for blk in range(len(b) // bs):
        o = blk * bs
        d, dmin = _f16s(b, o), _f16s(b, o + 2)
        scales = b[o + 4 : o + 16]
        qs = b[o + 16 : o + bs]
        vals = []
        for j in range(4):  # chunks of 32 bytes → sub-blocks 2j, 2j+1
            sc1, m1 = _scale_min_k4(scales, 2 * j)
            sc2, m2 = _scale_min_k4(scales, 2 * j + 1)
            chunk = qs[32 * j : 32 * j + 32]
            vals.extend(float(d) * sc1 * (c & 0x0F) - float(dmin) * m1 for c in chunk)
            vals.extend(float(d) * sc2 * (c >> 4) - float(dmin) * m2 for c in chunk)
        out.extend(vals)
    return np.array(out[:n], np.float32)


def ref_q5_k(b, n):
    bs = 2 + 2 + 12 + 32 + 128
    out = []
    for blk in range(len(b) // bs):
        o = blk * bs
        d, dmin = _f16s(b, o), _f16s(b, o + 2)
        scales = b[o + 4 : o + 16]
        qh = b[o + 16 : o + 48]
        ql = b[o + 48 : o + bs]
        vals, u1, u2 = [], 1, 2
        for j in range(4):
            sc1, m1 = _scale_min_k4(scales, 2 * j)
            sc2, m2 = _scale_min_k4(scales, 2 * j + 1)
            chunk = ql[32 * j : 32 * j + 32]
            vals.extend(
                float(d) * sc1 * ((c & 0x0F) + (16 if qh[l] & u1 else 0))
                - float(dmin) * m1
                for l, c in enumerate(chunk)
            )
            vals.extend(
                float(d) * sc2 * ((c >> 4) + (16 if qh[l] & u2 else 0))
                - float(dmin) * m2
                for l, c in enumerate(chunk)
            )
            u1 <<= 2
            u2 <<= 2
        out.extend(vals)
    return np.array(out[:n], np.float32)


def ref_q6_k(b, n):
    bs = 128 + 64 + 16 + 2
    out = []
    for blk in range(len(b) // bs):
        o = blk * bs
        ql = b[o : o + 128]
        qh = b[o + 128 : o + 192]
        sc = np.frombuffer(b, np.int8, count=16, offset=o + 192)
        d = _f16s(b, o + 208)
        vals = [0.0] * 256
        for h in range(2):
            yo, qlo, qho, so = 128 * h, 64 * h, 32 * h, 8 * h
            for l in range(32):
                is_ = l // 16
                q1 = ((ql[qlo + l] & 0x0F) | (((qh[qho + l] >> 0) & 3) << 4)) - 32
                q2 = ((ql[qlo + l + 32] & 0x0F) | (((qh[qho + l] >> 2) & 3) << 4)) - 32
                q3 = ((ql[qlo + l] >> 4) | (((qh[qho + l] >> 4) & 3) << 4)) - 32
                q4 = ((ql[qlo + l + 32] >> 4) | (((qh[qho + l] >> 6) & 3) << 4)) - 32
                vals[yo + l] = float(d) * sc[so + is_] * q1
                vals[yo + l + 32] = float(d) * sc[so + is_ + 2] * q2
                vals[yo + l + 64] = float(d) * sc[so + is_ + 4] * q3
                vals[yo + l + 96] = float(d) * sc[so + is_ + 6] * q4
        out.extend(vals)
    return np.array(out[:n], np.float32)


REFS = {
    8: ref_q8_0, 2: ref_q4_0, 3: ref_q4_1, 6: ref_q5_0, 7: ref_q5_1,
    12: ref_q4_k, 13: ref_q5_k, 14: ref_q6_k,
}


@pytest.mark.parametrize("ggml_type", sorted(REFS))
def test_dequant_matches_scalar_reference(ggml_type):
    block_bytes, block_elems, _ = _DEQUANT[ggml_type]
    nblocks = 5
    raw = rng.integers(0, 256, size=nblocks * block_bytes, dtype=np.uint8)
    # keep the f16 scale fields finite: clear their exponent top bits is
    # fiddly per-format, so instead just reject nan/inf lanes on both sides
    n = nblocks * block_elems
    info = GgufTensorInfo("t", (n,), ggml_type, 0)
    got = dequantize(info, raw)
    want = REFS[ggml_type](bytes(raw), n)
    both_finite = np.isfinite(got) & np.isfinite(want)
    assert both_finite.mean() > 0.5  # random f16 scales are mostly finite
    np.testing.assert_allclose(got[both_finite], want[both_finite], rtol=1e-5)


def test_plain_dtypes_roundtrip():
    x = rng.normal(size=24).astype(np.float32)
    assert np.array_equal(
        dequantize(GgufTensorInfo("t", (24,), 0, 0), x.view(np.uint8)), x
    )
    h = x.astype("<f2")
    np.testing.assert_allclose(
        dequantize(GgufTensorInfo("t", (24,), 1, 0), h.view(np.uint8)),
        h.astype(np.float32),
    )
    bf = (x.view(np.uint32) >> 16).astype("<u2")  # truncate to bf16
    got = dequantize(GgufTensorInfo("t", (24,), 30, 0), bf.view(np.uint8))
    np.testing.assert_allclose(got, x, rtol=1e-2)


def test_logical_layout_is_reversed_ne():
    # ne = (3, 2): 3 contiguous → numpy [2, 3]
    x = np.arange(6, dtype=np.float32)
    got = dequantize(GgufTensorInfo("t", (3, 2), 0, 0), x.view(np.uint8))
    assert got.shape == (2, 3)
    np.testing.assert_array_equal(got[0], [0, 1, 2])


# ---- end-to-end: tiny HF checkpoint exported to gguf loads identically ----

TINY = dict(
    vocab_size=512, hidden_size=64, intermediate_size=128,
    num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
    max_position_embeddings=256, rms_norm_eps=1e-5, rope_theta=10000.0,
)


def _permute(w: np.ndarray, n_head: int) -> np.ndarray:
    """llama.cpp's HF→GGUF q/k permutation (the converter's `permute`)."""
    out, inner = w.shape
    return (
        w.reshape(n_head, 2, out // n_head // 2, inner)
        .swapaxes(1, 2)
        .reshape(out, inner)
    )


def _write_gguf_with_data(path, meta, named_tensors):
    """GGUF v3 writer incl. aligned tensor data (f32)."""
    descs, blobs, off = [], [], 0
    for name, arr in named_tensors:
        arr = np.ascontiguousarray(arr, dtype="<f4")
        ne = tuple(reversed(arr.shape))  # ne[0] is the contiguous dim
        descs.append((name, ne, 0, off))
        raw = arr.tobytes()
        pad = (-len(raw)) % 32
        blobs.append(raw + b"\0" * pad)
        off += len(raw) + pad
    with open(path, "wb") as f:
        f.write(b"GGUF")
        f.write(struct.pack("<I", 3))
        f.write(struct.pack("<Q", len(descs)))
        f.write(struct.pack("<Q", len(meta)))
        for blob in meta:
            f.write(blob)
        for name, ne, ggml_type, offset in descs:
            f.write(_s(name))
            f.write(struct.pack("<I", len(ne)))
            for dim in ne:
                f.write(struct.pack("<Q", dim))
            f.write(struct.pack("<I", ggml_type))
            f.write(struct.pack("<Q", offset))
        f.write(b"\0" * ((-f.tell()) % 32))
        for blob in blobs:
            f.write(blob)


@pytest.fixture(scope="module")
def tiny_hf_dir(tmp_path_factory):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    d = str(tmp_path_factory.mktemp("hf"))
    torch.manual_seed(0)
    LlamaForCausalLM(LlamaConfig(**TINY, tie_word_embeddings=False)).save_pretrained(
        d, safe_serialization=True
    )
    return d


@pytest.fixture(scope="module")
def tiny_gguf(tmp_path_factory, tiny_hf_dir):
    """Export the tiny HF checkpoint the way llama.cpp's converter does."""
    from safetensors.numpy import load_file

    t = {}
    for fn in os.listdir(tiny_hf_dir):
        if fn.endswith(".safetensors"):
            t.update(load_file(os.path.join(tiny_hf_dir, fn)))
    h, kvh = TINY["num_attention_heads"], TINY["num_key_value_heads"]

    named = [("token_embd.weight", t["model.embed_tokens.weight"]),
             ("output_norm.weight", t["model.norm.weight"]),
             ("output.weight", t["lm_head.weight"])]
    for i in range(TINY["num_hidden_layers"]):
        p = f"model.layers.{i}."
        named += [
            (f"blk.{i}.attn_norm.weight", t[p + "input_layernorm.weight"]),
            (f"blk.{i}.attn_q.weight", _permute(t[p + "self_attn.q_proj.weight"], h)),
            (f"blk.{i}.attn_k.weight", _permute(t[p + "self_attn.k_proj.weight"], kvh)),
            (f"blk.{i}.attn_v.weight", t[p + "self_attn.v_proj.weight"]),
            (f"blk.{i}.attn_output.weight", t[p + "self_attn.o_proj.weight"]),
            (f"blk.{i}.ffn_norm.weight", t[p + "post_attention_layernorm.weight"]),
            (f"blk.{i}.ffn_gate.weight", t[p + "mlp.gate_proj.weight"]),
            (f"blk.{i}.ffn_up.weight", t[p + "mlp.up_proj.weight"]),
            (f"blk.{i}.ffn_down.weight", t[p + "mlp.down_proj.weight"]),
        ]

    meta = [
        _kv("general.architecture", T_STRING, _s("llama")),
        _kv("general.name", T_STRING, _s("tiny")),
        _kv("llama.context_length", T_UINT32, struct.pack("<I", TINY["max_position_embeddings"])),
        _kv("llama.embedding_length", T_UINT32, struct.pack("<I", TINY["hidden_size"])),
        _kv("llama.block_count", T_UINT32, struct.pack("<I", TINY["num_hidden_layers"])),
        _kv("llama.feed_forward_length", T_UINT32, struct.pack("<I", TINY["intermediate_size"])),
        _kv("llama.attention.head_count", T_UINT32, struct.pack("<I", h)),
        _kv("llama.attention.head_count_kv", T_UINT32, struct.pack("<I", kvh)),
        _kv("llama.rope.freq_base", T_FLOAT32, struct.pack("<f", TINY["rope_theta"])),
        _kv("llama.attention.layer_norm_rms_epsilon", T_FLOAT32, struct.pack("<f", TINY["rms_norm_eps"])),
        _kv("llama.vocab_size", T_UINT32, struct.pack("<I", TINY["vocab_size"])),
    ]
    path = str(tmp_path_factory.mktemp("gguf") / "tiny.gguf")
    _write_gguf_with_data(path, meta, named)
    return path


def test_gguf_config_matches_hf(tiny_gguf, tiny_hf_dir):
    cfg_g = ModelConfig.from_model_dir(tiny_gguf)
    with open(os.path.join(tiny_hf_dir, "config.json")) as f:
        cfg_h = ModelConfig.from_hf_config(json.load(f))
    for field in ("vocab_size", "hidden_size", "intermediate_size",
                  "num_layers", "num_heads", "num_kv_heads", "head_dim"):
        assert getattr(cfg_g, field) == getattr(cfg_h, field), field
    # float metadata rides as f32 in gguf — compare approximately
    assert cfg_g.rope_theta == pytest.approx(cfg_h.rope_theta)
    assert cfg_g.rms_norm_eps == pytest.approx(cfg_h.rms_norm_eps)


def test_gguf_params_match_safetensors_loader(tiny_gguf, tiny_hf_dir):
    from dynamo_tpu.models.loader import load_gguf_llama_params, load_llama_params

    cfg = ModelConfig.from_model_dir(tiny_gguf)
    pg = load_gguf_llama_params(tiny_gguf, cfg, jnp.float32)
    ph = load_llama_params(tiny_hf_dir, cfg, jnp.float32)
    assert set(pg) == set(ph)
    for k in ("embed", "final_norm", "lm_head"):
        np.testing.assert_allclose(pg[k], ph[k], rtol=1e-6, err_msg=k)
    for k in ph["layers"]:
        np.testing.assert_allclose(
            pg["layers"][k], ph["layers"][k], rtol=1e-6, err_msg=k
        )


def test_runner_serves_gguf(tiny_gguf, tiny_hf_dir):
    """ModelRunner(model_dir=<.gguf>) dispatches through the gguf loader
    and produces the same logits as the safetensors path."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.model_runner import ModelRunner

    def logits(model_dir):
        mcfg = ModelConfig.from_model_dir(model_dir)
        mcfg.attention_impl = "xla"
        cfg = EngineConfig(
            model=mcfg, max_batch_size=1, max_model_len=64, kv_block_size=8,
            num_kv_blocks=32, dtype="float32", prefill_buckets=[16],
        )
        runner = ModelRunner(cfg, model_dir=model_dir)
        s, bs, w = 16, cfg.kv_block_size, cfg.blocks_per_seq
        prompt = [1, 5, 9, 20, 33]
        tokens = np.zeros((1, s), np.int32)
        tokens[0, : len(prompt)] = prompt
        positions = np.arange(s, dtype=np.int32)[None, :]
        btab = np.zeros((1, w), np.int32)
        btab[0, : s // bs] = np.arange(s // bs)
        slot_map = (
            np.take_along_axis(btab, positions // bs, axis=1) * bs
            + positions % bs
        )
        slot_map[positions >= len(prompt)] = -1
        out, _ = runner.arch.forward(
            runner.params, mcfg, tokens, positions, runner.kv_cache,
            btab, slot_map, np.full(1, len(prompt), np.int32),
            mesh=runner.mesh,
        )
        return np.asarray(out)[0, : len(prompt)]

    np.testing.assert_allclose(
        logits(tiny_gguf), logits(tiny_hf_dir), rtol=2e-4, atol=2e-4
    )


def test_iter_rejects_truncated_data(tmp_path, tiny_gguf):
    g = read_gguf(tiny_gguf)
    clipped = tmp_path / "clip.gguf"
    size = g.data_offset + g.tensors[-1].offset + tensor_nbytes(g.tensors[-1])
    with open(tiny_gguf, "rb") as f:
        clipped.write_bytes(f.read(size - 100))
    g2 = read_gguf(str(clipped))
    with pytest.raises(Exception, match="exceeds"):
        list(iter_gguf_tensors(str(clipped), g2))
