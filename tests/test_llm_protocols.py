"""LLM protocol layer: model card, preprocessor, detokenizer, echo pipeline."""

import pytest

from dynamo_tpu.llm.backend import Backend, Decoder
from dynamo_tpu.llm.engines.echo import EchoEngineCore
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
from dynamo_tpu.llm.tokenizer import HFTokenizer
from dynamo_tpu.protocols.common import FinishReason
from dynamo_tpu.protocols.openai import (
    ChatCompletionChunk,
    ChatCompletionRequest,
    CompletionRequest,
    aggregate_chat_stream,
)
from dynamo_tpu.runtime.engine import Context, EngineError
from dynamo_tpu.runtime.pipeline import build_pipeline

from fixtures import make_model_dir


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    return make_model_dir(tmp_path_factory.mktemp("model"))


@pytest.fixture(scope="module")
def mdc(model_dir):
    return ModelDeploymentCard.from_local_path(model_dir, display_name="tiny-llama")


@pytest.fixture(scope="module")
def tokenizer(model_dir):
    return HFTokenizer.from_pretrained_dir(model_dir)


def test_mdc_from_local_path(mdc):
    assert mdc.display_name == "tiny-llama"
    assert mdc.slug == "tiny-llama"
    assert mdc.context_length == 256
    assert mdc.eos_token_ids and isinstance(mdc.eos_token_ids[0], int)
    assert "<|assistant|>" in mdc.chat_template
    assert mdc.checksum
    # wire round-trip preserves checksum
    assert ModelDeploymentCard.from_wire(mdc.to_wire()).checksum == mdc.checksum


def test_preprocess_chat_applies_template(mdc, tokenizer):
    pre = OpenAIPreprocessor(mdc, tokenizer)
    req = ChatCompletionRequest(
        model="tiny-llama",
        messages=[{"role": "user", "content": "hello world"}],
        max_tokens=10,
        temperature=0.5,
        stop=["STOP"],
    )
    out = pre.preprocess_chat(req)
    rendered = tokenizer.decode(out.token_ids, skip_special_tokens=False)
    assert "<|user|>" in rendered and "<|assistant|>" in rendered
    assert out.stop_conditions.max_tokens == 10
    assert out.stop_conditions.stop == ["STOP"]
    assert out.sampling_options.temperature == 0.5
    assert out.eos_token_ids == mdc.eos_token_ids
    assert out.mdc_checksum == mdc.checksum


def test_preprocess_rejects_oversized_prompt(mdc, tokenizer):
    pre = OpenAIPreprocessor(mdc, tokenizer)
    req = ChatCompletionRequest(
        model="m", messages=[{"role": "user", "content": "word " * 400}]
    )
    with pytest.raises(EngineError, match="exceeds context"):
        pre.preprocess_chat(req)


def test_decode_stream_matches_batch(tokenizer):
    text = "the quick brown fox jumps émojis ünïcode ✓ 中文"
    ids = tokenizer.encode(text)
    stream = tokenizer.decode_stream()
    out = []
    for tid in ids:
        delta = stream.step(tid)
        if delta:
            out.append(delta)
    assert "".join(out) == tokenizer.decode(ids)


def test_decoder_stop_string_jail(tokenizer):
    # "STOP" must never be surfaced, even partially, even if split over tokens
    text = "paris STOP extra"
    ids = tokenizer.encode(text)
    dec = Decoder(tokenizer, stop_strings=["STOP"])
    emitted = []
    finish = None
    for tid in ids:
        t, f = dec.step(tid)
        if t:
            emitted.append(t)
        if f:
            finish = f
            break
    full = "".join(emitted)
    assert finish == FinishReason.STOP
    assert "STOP" not in full
    assert "extra" not in full
    assert full.startswith("paris")


def test_decoder_partial_match_released(tokenizer):
    # a prefix of the stop string that never completes must be emitted
    dec = Decoder(tokenizer, stop_strings=["STOPXYZ"])
    ids = tokenizer.encode("go STOP go")
    emitted = []
    finish = None
    for tid in ids:
        t, f = dec.step(tid)
        if t:
            emitted.append(t)
        finish = f
    emitted.append(dec.flush() or "")
    assert finish is None
    assert "".join(emitted) == tokenizer.decode(ids)


def test_decoder_eos(tokenizer, mdc):
    eos = mdc.eos_token_ids[0]
    dec = Decoder(tokenizer, eos_token_ids=[eos])
    t, f = dec.step(eos)
    assert f == FinishReason.EOS and t is None
    # with ignore_eos, generation continues
    dec2 = Decoder(tokenizer, eos_token_ids=[eos], ignore_eos=True)
    _, f2 = dec2.step(eos)
    assert f2 is None


def test_decoder_hidden_stop_ids(tokenizer):
    dec = Decoder(tokenizer, hidden_stop_ids=[42])
    _, f = dec.step(42)
    assert f == FinishReason.STOP


@pytest.mark.asyncio
async def test_full_echo_pipeline(mdc, tokenizer):
    """OpenAI request → preprocessor → backend → echo engine → chunks."""
    pre = OpenAIPreprocessor(mdc, tokenizer)
    backend = Backend(tokenizer)
    engine = build_pipeline([pre, backend], EchoEngineCore())

    req = ChatCompletionRequest(
        model="tiny-llama",
        messages=[{"role": "user", "content": "hello world"}],
        max_tokens=64,
    )
    chunks = []
    async for chunk in engine.generate(Context(req)):
        chunks.append(ChatCompletionChunk.model_validate(chunk.model_dump()))
    assert chunks[0].choices[0].delta.role == "assistant"
    final = aggregate_chat_stream(chunks)
    # echo returns the templated prompt text
    assert "hello world" in (final.choices[0].message.content or "")
    assert final.choices[0].finish_reason in ("length", "stop")


@pytest.mark.asyncio
async def test_pipeline_respects_max_tokens(mdc, tokenizer):
    pre = OpenAIPreprocessor(mdc, tokenizer)
    backend = Backend(tokenizer)
    engine = build_pipeline([pre, backend], EchoEngineCore())
    req = ChatCompletionRequest(
        model="m",
        messages=[{"role": "user", "content": "a b c d e f g h i j"}],
        max_tokens=3,
    )
    total_tokens = 0
    async for chunk in engine.generate(Context(req)):
        pass  # just drain; count via usage below
    req2 = ChatCompletionRequest(
        model="m",
        messages=[{"role": "user", "content": "a b c d e f g h i j"}],
        max_tokens=3,
        stream_options={"include_usage": True},
    )
    chunks = [c async for c in engine.generate(Context(req2))]
    usage = [c for c in chunks if c.usage is not None]
    assert usage and usage[-1].usage.completion_tokens == 3


def test_annotated_envelope_roundtrip():
    from dynamo_tpu.protocols.annotated import Annotated

    a = Annotated.from_annotation("token_ids", [1, 2, 3])
    assert a.is_annotation and not a.is_error
    assert a.annotation_value() == [1, 2, 3]
    wire = a.to_wire()
    back = Annotated.maybe_from_wire(wire)
    assert back.event == "token_ids" and back.annotation_value() == [1, 2, 3]
    assert Annotated.maybe_from_wire({"choices": []}) is None
    err = Annotated.from_error("boom")
    assert err.is_error and not err.is_annotation


@pytest.mark.asyncio
async def test_preprocessor_emits_requested_annotations(mdc, tokenizer):
    from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
    from dynamo_tpu.protocols.annotated import Annotated
    from dynamo_tpu.protocols.openai import ChatCompletionRequest
    from dynamo_tpu.runtime.engine import AsyncEngine, Context

    class _NullEngine(AsyncEngine):
        async def generate(self, request):
            from dynamo_tpu.protocols.common import BackendOutput, FinishReason

            yield BackendOutput(
                text="ok", token_ids=[5], cum_tokens=1,
                finish_reason=FinishReason.STOP,
            )

    pre = OpenAIPreprocessor(mdc, tokenizer)
    req = ChatCompletionRequest(
        model="m",
        messages=[{"role": "user", "content": "hello"}],
        nvext={"annotations": ["formatted_prompt", "token_ids"]},
    )

    chunks = [c async for c in pre.generate(Context(req), _NullEngine())]
    anns = [c for c in chunks if isinstance(c, Annotated)]
    assert {a.event for a in anns} == {"formatted_prompt", "token_ids"}
    by_name = {a.event: a.annotation_value() for a in anns}
    assert "hello" in by_name["formatted_prompt"]
    assert isinstance(by_name["token_ids"], list) and by_name["token_ids"]
    # annotations precede the data chunks
    assert isinstance(chunks[0], Annotated)


def test_preprocess_maps_logit_bias_and_echo(mdc, tokenizer):
    from dynamo_tpu.protocols.openai import CompletionRequest

    pre = OpenAIPreprocessor(mdc, tokenizer)
    req = ChatCompletionRequest(
        model="tiny-llama",
        messages=[{"role": "user", "content": "hi"}],
        logit_bias={"42": 150.0, "7": -150.0},  # OpenAI string keys, clamped
    )
    out = pre.preprocess_chat(req)
    assert out.sampling_options.logit_bias == {42: 100.0, 7: -100.0}

    creq = CompletionRequest(model="tiny-llama", prompt="hello", echo=True)
    cout = pre.preprocess_completion(creq)
    assert cout.output_options.echo_prompt is True
    assert cout.sampling_options.logit_bias is None


async def test_completion_echo_prepends_prompt(mdc, tokenizer):
    """`echo: true` leads the completion stream with the prompt text."""
    from dynamo_tpu.llm.backend import BackendOutput

    pre = OpenAIPreprocessor(mdc, tokenizer)

    async def backend():
        yield BackendOutput(token_ids=[5], text="out!", cum_tokens=1, finish_reason=None)

    chunks = [
        r async for r in pre.completion_stream(
            "cmpl-1", "tiny-llama", backend(), prompt_tokens=2,
            echo_text="hello ",
        )
    ]
    texts = [c.choices[0].text for c in chunks if c.choices]
    assert texts == ["hello ", "out!"]


async def test_completion_stream_carries_legacy_logprobs(mdc, tokenizer):
    """Legacy completions `logprobs: N` must yield the per-chunk
    tokens/token_logprobs/top_logprobs/text_offset block — the engine
    computes them; dropping them in assembly is the accepted-but-ignored
    class round 1 banned."""
    from dynamo_tpu.llm.backend import BackendOutput
    from dynamo_tpu.protocols.common import TokenLogprob
    from dynamo_tpu.protocols.openai import aggregate_completion_stream

    pre = OpenAIPreprocessor(mdc, tokenizer)

    async def backend():
        yield BackendOutput(
            token_ids=[5], text="one", cum_tokens=1, finish_reason=None,
            logprobs=[TokenLogprob(5, -0.25, {5: -0.25, 7: -1.5})],
        )
        yield BackendOutput(
            token_ids=[9], text=" two", cum_tokens=2, finish_reason=None,
            logprobs=[TokenLogprob(9, -0.5, None)],
        )

    chunks = [
        r async for r in pre.completion_stream(
            "cmpl-1", "m", backend(), prompt_tokens=2,
        )
    ]
    blocks = [c.choices[0].logprobs for c in chunks if c.choices]
    assert all(b is not None for b in blocks)
    assert blocks[0]["token_logprobs"] == [-0.25]
    assert blocks[0]["top_logprobs"][0] and len(blocks[0]["top_logprobs"][0]) == 2
    # aggregation rebases offsets onto the accumulated text, and the
    # top_logprobs list stays token-aligned (None placeholders survive)
    agg = aggregate_completion_stream(chunks)
    lp = agg.choices[0].logprobs
    assert lp["token_logprobs"] == [-0.25, -0.5]
    assert lp["text_offset"] == [0, len("one")]
    assert len(lp["top_logprobs"]) == len(lp["tokens"])
    assert lp["top_logprobs"][1] is None


async def test_best_of_selects_highest_cum_logprob(mdc, tokenizer):
    """best_of=3, n=1: three candidates run, the highest-cumulative-
    logprob one returns, usage counts every candidate's tokens."""
    from dynamo_tpu.llm.backend import BackendOutput
    from dynamo_tpu.protocols.common import TokenLogprob
    from dynamo_tpu.runtime.engine import AsyncEngine

    pre = OpenAIPreprocessor(mdc, tokenizer)
    seen_seeds = []

    class FakeEngine(AsyncEngine):
        async def generate(self, ctx):
            seed = ctx.payload.sampling_options.seed
            seen_seeds.append(seed)
            # candidate quality keyed off the child seed offset
            lp = {10: -0.1, 11: -2.0, 12: -0.9}[seed]
            yield BackendOutput(
                token_ids=[5], text=f"cand{seed}", cum_tokens=2,
                finish_reason=None,
                logprobs=[TokenLogprob(5, lp, None)],
            )
            from dynamo_tpu.protocols.common import FinishReason
            yield BackendOutput(
                token_ids=[6], text="!", cum_tokens=2,
                finish_reason=FinishReason.STOP,
                logprobs=[TokenLogprob(6, -0.1, None)],
            )

    req = CompletionRequest(model="m", prompt="x", best_of=3, n=1, seed=10)
    chunks = [c async for c in pre.generate(Context(req), FakeEngine())]
    assert len(chunks) == 1
    resp = chunks[0]
    assert sorted(seen_seeds) == [10, 11, 12]
    assert len(resp.choices) == 1
    assert resp.choices[0].text == "cand10!"       # -0.2 beats -1.0/-2.1
    assert resp.choices[0].index == 0
    assert resp.choices[0].logprobs is None        # client asked for none
    assert resp.usage.completion_tokens == 6       # all three candidates


async def test_best_of_returns_n_ranked_with_logprobs(mdc, tokenizer):
    from dynamo_tpu.llm.backend import BackendOutput
    from dynamo_tpu.protocols.common import FinishReason, TokenLogprob
    from dynamo_tpu.runtime.engine import AsyncEngine

    pre = OpenAIPreprocessor(mdc, tokenizer)

    class FakeEngine(AsyncEngine):
        async def generate(self, ctx):
            seed = ctx.payload.sampling_options.seed
            lp = {7: -3.0, 8: -0.5, 9: -1.0}[seed]
            yield BackendOutput(
                token_ids=[5], text=f"c{seed}", cum_tokens=1,
                finish_reason=FinishReason.STOP,
                logprobs=[TokenLogprob(5, lp, {5: lp})],
            )

    req = CompletionRequest(
        model="m", prompt="x", best_of=3, n=2, seed=7, logprobs=1)
    chunks = [c async for c in pre.generate(Context(req), FakeEngine())]
    resp = chunks[0]
    assert [c.text for c in resp.choices] == ["c8", "c9"]  # ranked
    assert [c.index for c in resp.choices] == [0, 1]
    assert resp.choices[0].logprobs["token_logprobs"] == [-0.5]
    assert resp.choices[0].logprobs["top_logprobs"][0]


def test_best_of_rejections(mdc, tokenizer):
    pre = OpenAIPreprocessor(mdc, tokenizer)
    from dynamo_tpu.runtime.engine import EngineError

    with pytest.raises(EngineError):
        pre.preprocess_completion(
            CompletionRequest(model="m", prompt="x", best_of=1, n=2))
    with pytest.raises(EngineError):
        pre.preprocess_completion(
            CompletionRequest(model="m", prompt="x", best_of=3, stream=True))
    with pytest.raises(EngineError):
        pre.preprocess_completion(
            CompletionRequest(model="m", prompt="x", best_of=3, echo=True))
    with pytest.raises(EngineError):  # greedy candidates are identical
        pre.preprocess_completion(
            CompletionRequest(model="m", prompt="x", best_of=3,
                              temperature=0))
    with pytest.raises(EngineError):  # OpenAI's amplification cap
        pre.preprocess_completion(
            CompletionRequest(model="m", prompt="x", best_of=21))


def test_int_keyed_dicts_survive_msgpack_strict_decode():
    """logit_bias and top-logprob dicts ride msgpack planes whose decoders
    use the strict default (int map keys rejected) — wire forms must
    stringify keys and from_wire must restore ints."""
    import msgpack

    from dynamo_tpu.disagg.protocols import RemotePrefillRequest
    from dynamo_tpu.protocols.common import (
        EngineOutput, SamplingOptions, TokenLogprob,
    )

    so = SamplingOptions(temperature=0.5, logit_bias={42: -5.0, 7: 3.5})
    rt = SamplingOptions.from_wire(
        msgpack.unpackb(msgpack.packb(so.to_wire(), use_bin_type=True),
                        raw=False)
    )
    assert rt.logit_bias == {42: -5.0, 7: 3.5}

    out = EngineOutput(
        token_ids=[9],
        logprobs=[TokenLogprob(9, -0.1, {9: -0.1, 2: -2.0})],
    )
    rt_out = EngineOutput.from_wire(
        msgpack.unpackb(msgpack.packb(out.to_wire(), use_bin_type=True),
                        raw=False)
    )
    assert rt_out.logprobs[0].top == {9: -0.1, 2: -2.0}

    rpr = RemotePrefillRequest(
        request_id="r", engine_id="e", token_ids=[1], block_ids=[0],
        logit_bias={3: 1.0},
    )
    assert RemotePrefillRequest.from_wire(rpr.to_wire()).logit_bias == {3: 1.0}


def test_best_of_accepted_non_streaming(mdc, tokenizer):
    pre = OpenAIPreprocessor(mdc, tokenizer)
    # best_of > n: accepted for buffered selection (see _best_of)
    out = pre.preprocess_completion(
        CompletionRequest(model="m", prompt="x", best_of=3)
    )
    assert out.sampling_options.n in (None, 1)
    # best_of == n degenerates to plain n-way sampling — accepted
    out = pre.preprocess_completion(
        CompletionRequest(model="m", prompt="x", best_of=2, n=2)
    )
    assert out.sampling_options.n == 2


def test_logprobs_zero_edge_cases(mdc, tokenizer):
    """completions logprobs=0 and chat top_logprobs=0 mean 'chosen token's
    logprob, no alternatives' — NOT off, and not one alternative."""
    from dynamo_tpu.protocols.openai import CompletionRequest

    pre = OpenAIPreprocessor(mdc, tokenizer)
    out = pre.preprocess_completion(
        CompletionRequest(model="m", prompt="x", logprobs=0)
    )
    assert out.output_options.logprobs == 0
    out = pre.preprocess_chat(ChatCompletionRequest(
        model="m", messages=[{"role": "user", "content": "x"}],
        logprobs=True, top_logprobs=0,
    ))
    assert out.output_options.logprobs == 0
    out = pre.preprocess_chat(ChatCompletionRequest(
        model="m", messages=[{"role": "user", "content": "x"}],
        logprobs=True,
    ))
    assert out.output_options.logprobs == 0
    out = pre.preprocess_chat(ChatCompletionRequest(
        model="m", messages=[{"role": "user", "content": "x"}],
    ))
    assert out.output_options.logprobs is None


def test_nvext_greed_sampling_forces_greedy(mdc, tokenizer):
    from dynamo_tpu.protocols.openai import NvExt

    pre = OpenAIPreprocessor(mdc, tokenizer)
    out = pre.preprocess_chat(ChatCompletionRequest(
        model="m", messages=[{"role": "user", "content": "x"}],
        temperature=0.9, nvext=NvExt(greed_sampling=True),
    ))
    assert out.sampling_options.temperature == 0.0


def test_max_tokens_zero_means_empty_completion(mdc, tokenizer):
    from dynamo_tpu.protocols.openai import CompletionRequest

    pre = OpenAIPreprocessor(mdc, tokenizer)
    out = pre.preprocess_completion(
        CompletionRequest(model="m", prompt="x", max_tokens=0)
    )
    assert out.stop_conditions.max_tokens == 0


def test_preprocess_completion_sets_prompt_logprobs_for_echo(mdc, tokenizer):
    """OpenAI legacy completions: echo + logprobs asks the engine for
    prompt logprobs too; either flag alone does not."""
    from dynamo_tpu.protocols.openai import CompletionRequest

    pre = OpenAIPreprocessor(mdc, tokenizer)
    both = pre.preprocess_completion(CompletionRequest(
        model="m", prompt="hello", echo=True, logprobs=0,
    ))
    assert both.output_options.prompt_logprobs == 0
    echo_only = pre.preprocess_completion(CompletionRequest(
        model="m", prompt="hello", echo=True,
    ))
    assert echo_only.output_options.prompt_logprobs is None
    lp_only = pre.preprocess_completion(CompletionRequest(
        model="m", prompt="hello", logprobs=2,
    ))
    assert lp_only.output_options.prompt_logprobs is None


async def test_completion_echo_carries_prompt_logprobs(mdc, tokenizer):
    """With prompt_token_ids the echo chunk waits for the first backend
    output and renders its prompt_logprobs as the legacy logprobs block."""
    from dynamo_tpu.llm.backend import BackendOutput

    pre = OpenAIPreprocessor(mdc, tokenizer)
    ids = [3, 4]

    async def backend():
        yield BackendOutput(
            token_ids=[5], text="out!", cum_tokens=1, finish_reason=None,
            prompt_logprobs=[None] + [-0.5] * (len(ids) - 1),
        )

    chunks = [
        r async for r in pre.completion_stream(
            "cmpl-2", "m", backend(), prompt_tokens=len(ids),
            echo_text="hello world", prompt_token_ids=list(ids),
        )
    ]
    echo = chunks[0].choices[0]
    assert echo.text == "hello world"
    lp = echo.logprobs
    assert lp is not None
    assert len(lp["tokens"]) == len(ids)
    assert lp["token_logprobs"][0] is None
    assert all(v == -0.5 for v in lp["token_logprobs"][1:])
    assert lp["text_offset"][0] == 0


async def test_completion_echo_emitted_when_stream_yields_nothing(mdc, tokenizer):
    """ADVICE r3: with echo+logprobs the echo chunk waits for the first
    backend output — but if the stream ends with none (immediate
    cancel/zero-token completion) the client must still get the echoed
    prompt text, just without prompt logprobs."""
    pre = OpenAIPreprocessor(mdc, tokenizer)

    async def empty_backend():
        return
        yield  # pragma: no cover

    chunks = [
        r async for r in pre.completion_stream(
            "cmpl-3", "m", empty_backend(), prompt_tokens=2,
            echo_text="hello world", prompt_token_ids=[3, 4],
        )
    ]
    assert chunks, "echo chunk was dropped on an empty stream"
    echo = chunks[0].choices[0]
    assert echo.text == "hello world"
    assert echo.logprobs is None


def test_preprocess_guided_choice(mdc, tokenizer):
    """vLLM-style guided_choice (top level or nvext): the preprocessor
    carries the strings AND their canonical tokenizations so the engine
    can constrain without holding a tokenizer."""
    pre = OpenAIPreprocessor(mdc, tokenizer)
    req = ChatCompletionRequest(
        model="tiny-llama",
        messages=[{"role": "user", "content": "yes or no?"}],
        max_tokens=10,
        guided_choice=["yes", "no"],
    )
    out = pre.preprocess_chat(req)
    so = out.sampling_options
    assert so.guided_choice == ["yes", "no"]
    assert so.guided_choice_token_ids == [
        tokenizer.encode("yes", add_special_tokens=False),
        tokenizer.encode("no", add_special_tokens=False),
    ]
    # wire round-trip (token-level workers receive these)
    from dynamo_tpu.protocols.common import PreprocessedRequest

    back = PreprocessedRequest.from_wire(out.to_wire())
    assert back.sampling_options.guided_choice_token_ids == \
        so.guided_choice_token_ids

    # nvext placement works too
    req2 = ChatCompletionRequest(
        model="tiny-llama",
        messages=[{"role": "user", "content": "x"}],
        nvext={"guided_choice": ["a"]},
    )
    assert pre.preprocess_chat(req2).sampling_options.guided_choice == ["a"]

    # malformed lists are rejected loudly
    from dynamo_tpu.runtime.engine import EngineError

    bad = ChatCompletionRequest(
        model="tiny-llama",
        messages=[{"role": "user", "content": "x"}],
        guided_choice=["ok", ""],
    )
    with pytest.raises(EngineError, match="non-empty"):
        pre.preprocess_chat(bad)


def test_response_format_surface():
    # json_object / json_schema / text all validate at the type layer
    for rf in (
        {"type": "text"},
        {"type": "json_object"},
        {"type": "json_schema",
         "json_schema": {"name": "x", "schema": {"type": "object",
                                                 "properties": {"a": {}}}}},
    ):
        ChatCompletionRequest(
            model="m", messages=[{"role": "user", "content": "x"}],
            response_format=rf,
        )
    # unknown types and shapeless json_schema still 400
    with pytest.raises(Exception, match="response_format"):
        ChatCompletionRequest(
            model="m", messages=[{"role": "user", "content": "x"}],
            response_format={"type": "grammar"},
        )
    with pytest.raises(Exception, match="json_schema"):
        ChatCompletionRequest(
            model="m", messages=[{"role": "user", "content": "x"}],
            response_format={"type": "json_schema"},
        )


def test_preprocessor_guided_json(mdc, tokenizer):
    pre = OpenAIPreprocessor(mdc, tokenizer)
    req = ChatCompletionRequest(
        model="tiny-llama",
        messages=[{"role": "user", "content": "x"}],
        response_format={"type": "json_object"},
    )
    out = pre.preprocess_chat(req)
    assert out.sampling_options.guided_json == {"type": "json_object"}

    # vLLM-style extra field: the value IS the schema
    req2 = ChatCompletionRequest(
        model="tiny-llama",
        messages=[{"role": "user", "content": "x"}],
        guided_json={"type": "object", "properties": {"a": {"type": "string"}}},
    )
    out2 = pre.preprocess_chat(req2)
    assert out2.sampling_options.guided_json["type"] == "json_schema"
    assert out2.sampling_options.guided_json["schema"]["properties"]

    # unsupported schema keywords 400 at the door, not in the engine
    from dynamo_tpu.runtime.engine import EngineError

    bad = ChatCompletionRequest(
        model="tiny-llama",
        messages=[{"role": "user", "content": "x"}],
        guided_json={"type": "string", "pattern": "^a+$"},
    )
    with pytest.raises(EngineError, match="pattern"):
        pre.preprocess_chat(bad)

    # mutually exclusive with guided_choice
    both = ChatCompletionRequest(
        model="tiny-llama",
        messages=[{"role": "user", "content": "x"}],
        guided_choice=["a"],
        guided_json={"type": "object", "properties": {"a": {}}},
    )
    with pytest.raises(EngineError, match="exclusive"):
        pre.preprocess_chat(both)
