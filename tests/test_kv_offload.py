"""Host-memory KV offload tier: eviction→offload, prefix restore, LRU,
and end-to-end consistency of restored KV with recomputed KV."""

import asyncio

import numpy as np

import jax.numpy as jnp

from dynamo_tpu.engine.block_allocator import BlockAllocator
from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.model_runner import ModelRunner
from dynamo_tpu.engine.scheduler import Scheduler
from dynamo_tpu.kv import KvHostTier
from dynamo_tpu.models.loader import load_llama_params
from dynamo_tpu.tokens import compute_block_hashes

from test_disagg import _collect, _greedy_request
from test_jax_engine import hf_model_dir, hf_logits, TINY  # noqa: F401


class FakeStore:
    """In-memory stand-in for the runner's gather/scatter (unit tests)."""

    def __init__(self, num_blocks):
        self.data = {i: None for i in range(num_blocks)}

    def write(self, bid, value):
        self.data[bid] = value

    def gather(self, ids):
        k = np.stack([self.data[i] for i in ids])[None]  # [1, n] fake L dim
        return k, k.copy()

    def scatter(self, ids, k, v):
        for j, bid in enumerate(ids):
            self.data[bid] = k[0, j]


def test_host_tier_offload_restore_lru():
    store = FakeStore(8)
    tier = KvHostTier(store.gather, store.scatter, capacity_blocks=2)
    for bid, h in [(0, 100), (1, 101), (2, 102)]:
        store.write(bid, np.full(4, bid, np.float32))
        tier.offload(h, bid)
    # offload only stages; all three visible until drain...
    assert tier.has(100) and tier.has(101) and tier.has(102)
    tier.drain()
    # ...then capacity 2 → hash 100 was LRU-evicted
    assert not tier.has(100) and tier.has(101) and tier.has(102)
    assert tier.evicted_total == 1
    # restore 101 into slot 5
    tier.restore([101], [5])
    np.testing.assert_array_equal(store.data[5], np.full(4, 1, np.float32))
    assert tier.restored_total == 1
    # match_extension walks the contiguous resident run
    assert tier.match_extension([101, 102, 999], 0) == [101, 102]
    assert tier.match_extension([999, 101], 0) == []


def test_allocator_offloads_on_eviction_and_restores():
    store = FakeStore(4)
    tier = KvHostTier(store.gather, store.scatter, capacity_blocks=8)
    alloc = BlockAllocator(4, 4, True, tier2=tier)

    # prompt A fills all 4 blocks (last block partial → 3 registered)
    prompt_a = list(range(1, 14))  # 13 tokens → 4 blocks, 3 complete
    blocks_a, cached = alloc.allocate_prompt(prompt_a)
    assert cached == 0
    hashes_a = compute_block_hashes(prompt_a, 4)
    parent = None
    for bid, h in zip(blocks_a, hashes_a):
        store.write(bid, np.full(4, h % 97, np.float32))
        alloc.register_complete(bid, h, parent)
        parent = h
    alloc.free_blocks(blocks_a)

    # prompt B needs all blocks → evicts A's blocks, offloading the hashed ones
    prompt_b = list(range(100, 113))
    blocks_b, _ = alloc.allocate_prompt(prompt_b)
    assert tier.offloaded_total == 3
    assert all(tier.has(h) for h in hashes_a)
    alloc.free_blocks(blocks_b)

    # prompt A again: HBM blocks are gone (B overwrote), host tier restores
    probe = alloc.probe_prefix(prompt_a)
    assert alloc.cached_tokens(probe) == 12  # 3 complete blocks
    blocks_a2, cached2 = alloc.allocate_prompt(prompt_a, probe=probe)
    assert cached2 == 12
    assert tier.restored_total == 3
    # restored data landed in the newly allocated slots
    for bid, h in zip(blocks_a2[:3], hashes_a):
        np.testing.assert_array_equal(store.data[bid], np.full(4, h % 97, np.float32))


async def test_offload_e2e_restored_kv_matches_recompute(hf_model_dir):
    """Evict a prompt's KV to host, restore it, and check generation is
    identical to a fresh engine (restored KV ≡ recomputed KV)."""
    cfg = ModelConfig.from_model_dir(hf_model_dir)
    # tiny HBM cache (4 blocks of 8 = 32 tokens) so prompts evict each other
    econfig = EngineConfig(
        model=cfg, max_batch_size=2, max_model_len=64, kv_block_size=8,
        num_kv_blocks=4, dtype="float32", host_kv_blocks=32,
    )
    params = load_llama_params(hf_model_dir, cfg, jnp.float32)
    runner = ModelRunner(econfig, params=params)
    sched = Scheduler(runner, econfig)
    assert sched.allocator.tier2 is not None
    sched.start()

    prompt_a = [1, 17, 43, 99, 7, 3, 250, 12, 5, 77, 8, 21, 33, 44, 55, 66, 9, 2]
    prompt_b = [2, 30, 31, 32, 33, 34, 35, 36, 37, 38, 39, 40, 41, 42, 43, 44, 45, 46]

    async def run(prompt, rid):
        er = _greedy_request(rid, prompt, max_tokens=6)
        sched.add_request(er)
        return await _collect(er)

    out_a1 = await run(prompt_a, "a1")
    out_b = await run(prompt_b, "b")   # evicts A's blocks → host tier
    tier = sched.allocator.tier2
    assert tier.offloaded_total > 0
    out_a2 = await run(prompt_a, "a2")  # restored from host, not recomputed
    assert tier.restored_total > 0
    assert out_a2 == out_a1
    m = sched.metrics()
    assert m["host_kv_restored_total"] == tier.restored_total
    await sched.stop()

    # fresh engine with no caching history → ground truth
    runner2 = ModelRunner(econfig, params=params)
    sched2 = Scheduler(runner2, econfig)
    sched2.start()
    er = _greedy_request("fresh", prompt_a, max_tokens=6)
    sched2.add_request(er)
    fresh = await _collect(er)
    await sched2.stop()
    assert out_a2 == fresh
