"""Watch-driven operator loop + Lease leader election.

Reference analog: deploy/dynamo/operator's controller-runtime event
machinery and cmd/main.go LeaderElection. The loop is driven from
in-memory event streams and the election from an in-memory CAS store —
no kubectl in the loop.
"""

import threading

from dynamo_tpu.deploy.leader import InMemoryLeases, LeaderElector
from dynamo_tpu.deploy.operator import InMemoryKube, Reconciler
from dynamo_tpu.deploy.watch import iter_watch_events, watch_loop


def _cr(name="g1", namespace="default", services=None, generation=1):
    return {
        "metadata": {"name": name, "namespace": namespace,
                     "generation": generation, "uid": "u-" + name},
        "spec": {"namespace": "public", "services": services or {}},
    }


def test_iter_watch_events_handles_split_and_concatenated_docs():
    docs = (
        '{"type": "ADDED", "object": {"a": 1}}\n'
        '{\n  "type": "MODIFIED",\n  "object": {"a": 2}\n}'
        '{"type": "DELETED", "object": {"a": 3}}'
    )
    # feed in awkward chunk sizes (split mid-document)
    chunks = [docs[i:i + 7] for i in range(0, len(docs), 7)]
    events = list(iter_watch_events(chunks))
    assert [e["type"] for e in events] == ["ADDED", "MODIFIED", "DELETED"]
    assert [e["object"]["a"] for e in events] == [1, 2, 3]


def _run_watch_once(reconciler, listed, streams):
    """Run watch_loop until the streams are exhausted, then stop it."""
    stop = threading.Event()
    it = iter(streams)

    def open_stream():
        try:
            return next(it)
        except StopIteration:
            stop.set()
            return []

    watch_loop(reconciler, lambda: listed, open_stream, stop=stop,
               reconnect_backoff_s=0.0)


def test_watch_events_reconcile_and_finalize():
    kube = InMemoryKube()
    rec = Reconciler(kube)
    cr = _cr("g1")
    # initial relist is empty; the stream delivers ADDED then DELETED
    _run_watch_once(rec, [], [[
        {"type": "ADDED", "object": cr},
        {"type": "DELETED", "object": cr},
    ]])
    assert kube.objects == {}  # children created by ADDED, torn down by DELETED


def test_watch_added_creates_children():
    kube = InMemoryKube()
    rec = Reconciler(kube)
    cr = _cr("g1")
    _run_watch_once(rec, [cr], [[{"type": "ADDED", "object": cr}]])
    assert any("g1-frontend" in k for k in kube.objects)
    assert any("g1-dynstore" in k for k in kube.objects)


def test_relist_finalizes_cr_deleted_while_disconnected():
    kube = InMemoryKube()
    rec = Reconciler(kube)
    cr = _cr("g1")
    # stream 1: CR appears. stream 2 opens after a gap during which the
    # CR was deleted — the relist (now empty) must finalize it even
    # though no DELETED event was ever observed.
    _run_watch_once(rec, [], [[{"type": "ADDED", "object": cr}], []])
    assert kube.objects == {}


def test_watch_list_failure_is_not_no_crs():
    kube = InMemoryKube()
    rec = Reconciler(kube)
    cr = _cr("g1")
    _run_watch_once(rec, [cr], [[{"type": "ADDED", "object": cr}]])
    assert kube.objects
    # a failed relist (None) must not finalize anything
    stop = threading.Event()
    calls = {"n": 0}

    def failing_list():
        calls["n"] += 1
        if calls["n"] >= 3:
            stop.set()
        return None

    watch_loop(rec, failing_list, lambda: [], stop=stop,
               reconnect_backoff_s=0.0)
    assert kube.objects  # children survived the API outage


def test_watch_churn_converges_to_final_cr_set():
    """Arbitrary interleavings of ADDED/MODIFIED/DELETED across several
    reconnects must converge: children exist exactly for the CRs alive
    at the end, regardless of event order or drops between streams."""
    import random

    rng = random.Random(7)
    names = [f"g{i}" for i in range(5)]
    kube = InMemoryKube()
    rec = Reconciler(kube)
    alive = {}
    streams = []
    for _ in range(6):  # six reconnects
        events = []
        for _ in range(8):
            name = rng.choice(names)
            if name in alive and rng.random() < 0.4:
                events.append({"type": "DELETED", "object": alive.pop(name)})
            else:
                cr = _cr(name, generation=rng.randrange(100))
                alive[name] = cr
                events.append({
                    "type": rng.choice(["ADDED", "MODIFIED"]), "object": cr,
                })
        # drop a random suffix: the relist must repair what the stream
        # never delivered (deletions between streams)
        streams.append(events[: rng.randrange(4, len(events) + 1)])
        # CRs deleted in the dropped suffix are still deleted cluster-side
        for e in events[len(streams[-1]):]:
            key = e["object"]["metadata"]["name"]
            if e["type"] == "DELETED":
                alive.pop(key, None)
            else:
                alive[key] = e["object"]

    _run_watch_once(rec, list(alive.values()), streams)
    have = {
        m["metadata"]["labels"]["app.kubernetes.io/instance"]
        for m in kube.objects.values()
    }
    assert have == set(alive)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_leader_first_comer_acquires():
    leases = InMemoryLeases()
    a = LeaderElector(leases, "a", clock=FakeClock())
    assert a.try_acquire_or_renew()
    assert a.try_acquire_or_renew()  # renewal keeps the lease


def test_leader_follower_waits_full_ttl_then_takes_over():
    leases = InMemoryLeases()
    clock_a, clock_b = FakeClock(), FakeClock()
    a = LeaderElector(leases, "a", lease_duration_s=15, clock=clock_a)
    b = LeaderElector(leases, "b", lease_duration_s=15, clock=clock_b)
    assert a.try_acquire_or_renew()
    # b just arrived: holder looks alive until a full TTL passes locally
    assert not b.try_acquire_or_renew()
    clock_b.t = 10.0
    assert not b.try_acquire_or_renew()
    # a keeps renewing → b's observation fingerprint changes → TTL restarts
    assert a.try_acquire_or_renew()
    clock_b.t = 20.0
    assert not b.try_acquire_or_renew()
    # a goes silent; a full TTL after b's last fingerprint change, b wins
    clock_b.t = 36.0
    assert b.try_acquire_or_renew()
    # the deposed leader's next renewal must fail (CAS conflict)
    assert not a.try_acquire_or_renew()


def test_leader_renew_time_is_valid_microtime_and_increases():
    # the apiserver rejects a Lease whose spec.renewTime is not an
    # RFC3339 MicroTime — and observers rely on every renewal producing
    # a *different* stamp
    from datetime import datetime

    elector = LeaderElector(InMemoryLeases(), "a", clock=FakeClock())
    stamps = [elector._spec(0)["renewTime"] for _ in range(3)]
    for s in stamps:
        datetime.strptime(s, "%Y-%m-%dT%H:%M:%S.%fZ")
    assert stamps == sorted(set(stamps))


def test_leader_kubectl_read_raises_on_non_notfound_failure():
    # an API blip must not read as "lease absent" (a create attempt
    # would then fail and depose a healthy leader); only NotFound may
    # map to (None, None)
    import pytest

    from dynamo_tpu.deploy.leader import KubectlLeases

    with pytest.raises(Exception):
        KubectlLeases(kubectl="false").read("default", "x")


def test_leader_kubectl_write_classifies_structured_reason_only():
    # only kubectl's structured status reason — "Error from server
    # (Conflict)" / "(AlreadyExists)" — means a lost CAS race; an
    # unrelated error merely *containing* the word "conflict" must raise
    from dynamo_tpu.deploy.leader import KubectlLeases

    cas = KubectlLeases._CAS_REASON
    assert cas.search('Error from server (Conflict): Operation cannot be '
                      'fulfilled on leases.coordination.k8s.io "x"')
    assert cas.search('error from server (AlreadyExists): leases "x" '
                      'already exists')
    assert not cas.search('error validating data: field conflict in spec')
    assert not cas.search('dial tcp: lookup apiserver: conflict-zone.local '
                          'no such host')


def test_leader_cas_conflict_single_winner():
    leases = InMemoryLeases()
    electors = [LeaderElector(leases, f"e{i}", clock=FakeClock())
                for i in range(4)]
    wins = [e.try_acquire_or_renew() for e in electors]
    assert sum(wins) == 1


def test_watch_failed_reconcile_abandons_stream_for_early_relist():
    # a transient reconcile failure on a quiet cluster must not wait for
    # the resync timeout: the loop abandons the stream and the relist
    # retries within the base delay
    kube = InMemoryKube()
    rec = Reconciler(kube)
    cr = _cr("g1")
    fail_once = {"n": 0}
    orig = rec.reconcile

    def flaky(c):
        fail_once["n"] += 1
        if fail_once["n"] == 1:
            raise RuntimeError("transient apply failure")
        return orig(c)

    rec.reconcile = flaky
    # stream 1 delivers ADDED (reconcile fails → stream abandoned); the
    # relist before stream 2 retries and succeeds
    _run_watch_once(rec, [cr], [[{"type": "ADDED", "object": cr}], []])
    assert fail_once["n"] >= 2
    assert any("g1-frontend" in k for k in kube.objects)


class FlakyLeases(InMemoryLeases):
    """Raises on demand to model an unreachable API."""

    def __init__(self):
        super().__init__()
        self.fail = False

    def read(self, namespace, name):
        if self.fail:
            raise RuntimeError("apiserver unreachable")
        return super().read(namespace, name)


def test_leader_transient_api_blip_does_not_depose():
    clock = FakeClock()
    leases = FlakyLeases()
    elector = LeaderElector(leases, "a", lease_duration_s=15,
                            renew_deadline_s=10, clock=clock)
    assert elector.try_acquire_or_renew()
    stop = threading.Event()
    # one failed renewal inside the deadline: retry, not step-down
    leases.fail = True
    import pytest
    with pytest.raises(RuntimeError):
        elector.try_acquire_or_renew()
    leases.fail = False
    assert elector.try_acquire_or_renew()
    assert not stop.is_set()


def test_leader_steps_down_past_renew_deadline():
    # real clock: renewal keeps failing past the deadline → step down
    leases = FlakyLeases()
    elector = LeaderElector(leases, "a", lease_duration_s=0.3,
                            renew_interval_s=0.01, renew_deadline_s=0.05)
    assert elector.try_acquire_or_renew()
    leases.fail = True
    stop = threading.Event()
    t = threading.Thread(target=elector._renew_until_lost, args=(stop,),
                         daemon=True)
    t.start()
    assert stop.wait(timeout=5.0), "leader failed to step down"
    t.join(timeout=2.0)


def test_leader_run_leads_then_steps_down_when_lease_lost():
    leases = InMemoryLeases()
    clock = FakeClock()
    elector = LeaderElector(leases, "a", renew_interval_s=0.01, clock=clock)
    led = threading.Event()
    stop = threading.Event()

    def lead():
        led.set()
        # usurp the lease out from under the leader; its renewer must
        # notice the CAS conflict and set stop
        other = LeaderElector(leases, "b", clock=clock)
        spec, version = leases.read("default", "dynamo-tpu-operator")
        assert leases.write("default", "dynamo-tpu-operator",
                            other._spec(1), version)
        assert stop.wait(timeout=5.0)

    elector.run(stop, lead)
    assert led.is_set()
    assert stop.is_set()
