"""Block-manager reuse semantics: priority eviction classes, inflight
match staging, pin fences, and asynchronous host-tier offload overlap.

VERDICT r3 items 5+7 — parity with the reference's
lib/llm/src/kv/{reuse,reserved,manager}.rs: priority + FIFO reuse
queues, match-inflight-then-reusable staging, fences so a block with a
copy in flight can't be reclaimed, and offload that never stalls the
decode loop on device→host materialization.
"""

import time

import numpy as np
import pytest

from dynamo_tpu.engine.block_allocator import BlockAllocator, KvEventSink
from dynamo_tpu.kv import KvHostTier
from dynamo_tpu.tokens import compute_block_hashes

BS = 4  # block size for all tests


def fill_and_free(alloc, prompt, store=None):
    """Allocate a prompt, register its complete blocks, free it.
    Returns (block_ids, hashes)."""
    blocks, _ = alloc.allocate_prompt(prompt)
    hashes = compute_block_hashes(prompt, BS)
    parent = None
    n_complete = len(prompt) // BS
    for bid, h in zip(blocks[:n_complete], hashes):
        if store is not None:
            store.write(bid, np.full(4, h % 251, np.float32))
        alloc.register_complete(bid, h, parent)
        parent = h
    alloc.free_blocks(blocks)
    return blocks, hashes


class FakeStore:
    def __init__(self, num_blocks):
        self.data = {i: None for i in range(num_blocks)}

    def write(self, bid, value):
        self.data[bid] = value

    def gather(self, ids):
        k = np.stack([self.data[i] for i in ids])[None]
        return k, k.copy()

    def scatter(self, ids, k, v):
        for j, bid in enumerate(ids):
            self.data[bid] = k[0, j]


# ---------------------------------------------------------------------------
# priority classes
# ---------------------------------------------------------------------------


def test_priority_eviction_order():
    """Eviction drains the lowest priority class first, FIFO within a
    class (reference kv/reuse.rs PriorityKey ordering)."""
    removed = []
    events = KvEventSink(on_removed=lambda hs: removed.extend(hs))
    alloc = BlockAllocator(6, BS, events=events)

    # three 2-block prompts fill the cache, then free → 6 reusable blocks
    prompts = [list(range(s, s + 8)) for s in (10, 100, 200)]
    hashes = [fill_and_free(alloc, p)[1] for p in prompts]
    # prompt 1 (middle) is important: retain longest
    alloc.set_priority(hashes[1], 5)

    # evicting all six: priority-0 classes go first in free order
    # (prompt0's blocks, then prompt2's), then the priority-5 class
    order = []
    for _ in range(6):
        removed.clear()
        alloc.allocate_block()
        order.extend(removed)
    assert order[:2] == list(hashes[0])
    assert order[2:4] == list(hashes[2])
    assert order[4:6] == list(hashes[1])


def test_set_priority_rekeys_already_pooled_blocks():
    alloc = BlockAllocator(4, BS)
    _, h_a = fill_and_free(alloc, list(range(8)))
    _, h_b = fill_and_free(alloc, list(range(50, 58)))
    # both pooled at priority 0; promote A afterwards
    alloc.set_priority(h_a, 3)
    removed = []
    alloc.events.on_removed = lambda hs: removed.extend(hs)
    for _ in range(4):
        alloc.allocate_block()
    assert removed[:2] == list(h_b)   # B (prio 0) evicted first
    assert removed[2:] == list(h_a)


# ---------------------------------------------------------------------------
# inflight-then-reusable match staging
# ---------------------------------------------------------------------------


def test_shared_prefix_storm_shares_inflight_blocks():
    """Many concurrent sequences over one prefix: the prefix blocks are
    shared by refcount (reference kv/reserved.rs inflight matching) —
    never duplicated, never double-used, and the staging counters split
    inflight vs reusable matches."""
    alloc = BlockAllocator(32, BS)
    prefix = list(range(1, 17))           # 4 complete blocks
    seqs = []

    # first sequence computes the prefix and keeps it live (inflight)
    blocks0, cached0 = alloc.allocate_prompt(prefix + [77])
    assert cached0 == 0
    hashes = compute_block_hashes(prefix, BS)
    parent = None
    for bid, h in zip(blocks0[:4], hashes):
        alloc.register_complete(bid, h, parent)
        parent = h
    seqs.append(blocks0)

    # a storm of sequences with the same prefix while seq0 is STILL live
    for i in range(8):
        blocks, cached = alloc.allocate_prompt(prefix + [100 + i])
        assert cached == 16
        assert blocks[:4] == blocks0[:4]      # shared, not recomputed
        seqs.append(blocks)
    assert alloc.matched_inflight_total == 8 * 4
    assert alloc.matched_reusable_total == 0
    for bid in blocks0[:4]:
        assert alloc.refcount[bid] == 9

    # all release → prefix blocks pooled exactly once each
    for blocks in seqs:
        alloc.free_blocks(blocks)
    for bid in blocks0[:4]:
        assert bid in alloc.reusable
        assert alloc.refcount.get(bid, 0) == 0

    # next match is a REUSABLE-stage hit
    blocks2, cached2 = alloc.allocate_prompt(prefix + [500])
    assert cached2 == 16
    assert alloc.matched_reusable_total == 4
    alloc.free_blocks(blocks2)


def test_no_double_use_under_churn():
    """Arbitrary allocate/free churn: a block id is never live in two
    places (sum of per-sequence refs == allocator refcount)."""
    rng = np.random.default_rng(7)
    alloc = BlockAllocator(16, BS)
    live = {}  # name → block list
    for step in range(300):
        if live and (len(live) > 5 or rng.random() < 0.45):
            name = list(live)[rng.integers(len(live))]
            alloc.free_blocks(live.pop(name))
        else:
            start = int(rng.integers(0, 8)) * BS
            length = int(rng.integers(5, 20))
            prompt = list(range(start, start + length))
            try:
                blocks, _ = alloc.allocate_prompt(prompt)
            except MemoryError:
                continue
            hashes = compute_block_hashes(prompt, BS)
            parent = None
            for bid, h in zip(blocks[: len(prompt) // BS], hashes):
                alloc.register_complete(bid, h, parent)
                parent = h
            live[f"s{step}"] = blocks
        # invariant: allocator refcounts == external holds
        holds = {}
        for blocks in live.values():
            for bid in blocks:
                holds[bid] = holds.get(bid, 0) + 1
        assert holds == {k: v for k, v in alloc.refcount.items() if v > 0}
        # and no live block is evictable
        for bid in holds:
            assert bid not in alloc.reusable


# ---------------------------------------------------------------------------
# pins / fences
# ---------------------------------------------------------------------------


def test_pinned_block_survives_eviction_pressure():
    alloc = BlockAllocator(4, BS)
    _, h = fill_and_free(alloc, list(range(8)))     # 2 hashed reusable
    bid = alloc.by_hash[h[0]]
    alloc.pin_blocks([bid])
    taken = [alloc.allocate_block() for _ in range(3)]  # 2 free + 1 evict
    assert bid not in taken                  # the pinned block was skipped
    assert alloc.by_hash.get(h[0]) == bid    # still matchable
    with pytest.raises(MemoryError):
        alloc.allocate_block()               # only the pinned block remains
    alloc.unpin_blocks([bid])
    assert alloc.allocate_block() == bid     # now reclaimable


def test_free_of_pinned_block_defers_until_unpin():
    alloc = BlockAllocator(4, BS)
    blocks, _ = alloc.allocate_prompt(list(range(6)))
    alloc.pin_blocks(blocks[:1])
    alloc.free_blocks(blocks)
    # the pinned block's release deferred: not reusable, not free
    assert blocks[0] not in alloc.reusable
    assert blocks[0] not in alloc.free
    assert blocks[1] in alloc.free
    alloc.unpin_blocks(blocks[:1])
    assert blocks[0] in alloc.free


def test_pins_are_counted_across_consumers():
    """Two consumers fencing the same block: the fence must hold until
    the LAST unpin (a set would drop it at the first)."""
    alloc = BlockAllocator(4, BS)
    _, h = fill_and_free(alloc, list(range(8)))
    bid = alloc.by_hash[h[0]]
    alloc.pin_blocks([bid])      # consumer 1
    alloc.pin_blocks([bid])      # consumer 2
    alloc.unpin_blocks([bid])    # consumer 1 done
    taken = [alloc.allocate_block() for _ in range(3)]
    assert bid not in taken      # consumer 2 still holds the fence
    alloc.unpin_blocks([bid])
    assert alloc.allocate_block() == bid


def test_block_reacquired_while_pinned_cancels_deferred_free():
    """free → (pinned, deferred) → re-matched by a new prompt → unpin:
    the deferred free must NOT fire — the block is live again, and
    releasing it would let eviction corrupt a live sequence's KV."""
    alloc = BlockAllocator(8, BS)
    prompt = list(range(1, 9))   # 2 blocks, both complete
    blocks, hashes = fill_and_free(alloc, prompt)
    # re-take it live, pin (transfer in flight), then free the sequence
    blocks2, cached = alloc.allocate_prompt(prompt + [99])
    assert blocks2[:1] == blocks[:1]
    alloc.pin_blocks(blocks2[:1])
    alloc.free_blocks(blocks2)   # block 0 deferred (pinned)
    # a NEW prompt re-acquires the deferred block before the unpin
    blocks3, _ = alloc.allocate_prompt(prompt + [77])
    assert blocks3[0] == blocks2[0]
    assert alloc.refcount[blocks3[0]] == 1
    alloc.unpin_blocks(blocks2[:1])
    # the live block must not have been released to the pool
    assert blocks3[0] not in alloc.reusable
    assert blocks3[0] not in alloc.free
    assert alloc.refcount[blocks3[0]] == 1
    alloc.free_blocks(blocks3)
    assert blocks3[0] in alloc.reusable  # normal release once truly free


def test_restore_targets_are_fenced_during_restore():
    """While the host tier writes a restore, the target slots are pinned
    (a reclaim racing the copy would corrupt the restored prefix)."""
    store = FakeStore(8)
    tier = KvHostTier(store.gather, store.scatter, capacity_blocks=16)
    alloc = BlockAllocator(8, BS, tier2=tier)
    observed = {}

    orig_restore = tier.restore

    def spying_restore(hashes, bids):
        observed["pinned_during"] = all(b in alloc.pinned for b in bids)
        orig_restore(hashes, bids)

    tier.restore = spying_restore
    _, h_a = fill_and_free(alloc, list(range(1, 13)), store)
    # force A out of HBM entirely
    big = list(range(100, 100 + 8 * BS))
    blocks_b, _ = alloc.allocate_prompt(big)
    alloc.free_blocks(blocks_b)
    # A's prefix restores from host → spying_restore must see pins
    blocks_a2, cached = alloc.allocate_prompt(list(range(1, 13)))
    assert cached > 0 and observed["pinned_during"]
    assert not alloc.pinned                   # released after the restore
    alloc.free_blocks(blocks_a2)


# ---------------------------------------------------------------------------
# async offload staging
# ---------------------------------------------------------------------------


class SlowD2H:
    """Device-array stand-in whose host materialization completes
    ``delay`` seconds after the copy STARTED (copy_to_host_async), the
    way a real D2H DMA behaves."""

    def __init__(self, arr, delay):
        self.arr = arr
        self.delay = delay
        self.t0 = None

    def copy_to_host_async(self):
        if self.t0 is None:
            self.t0 = time.monotonic()

    def __array__(self, dtype=None, copy=None):
        start = self.t0 if self.t0 is not None else time.monotonic()
        remaining = start + self.delay - time.monotonic()
        if remaining > 0:
            time.sleep(remaining)
        return self.arr if dtype is None else self.arr.astype(dtype)


def test_offload_dispatch_does_not_block_on_d2h():
    """offload_batch must cost dispatch time only; the D2H latency is
    paid by drain — and not even there if compute overlapped it
    (reference CopyStream::trigger_layer overlap, kv/layer.rs:100-1140)."""
    store = FakeStore(8)
    DELAY = 0.2

    def slow_gather(ids):
        k, v = store.gather(ids)
        return SlowD2H(k, DELAY), SlowD2H(v, DELAY)

    tier = KvHostTier(slow_gather, store.scatter, capacity_blocks=16)
    for bid in range(4):
        store.write(bid, np.full(4, bid, np.float32))

    t0 = time.monotonic()
    tier.offload_batch([(100 + b, b) for b in range(4)])
    dispatch_cost = time.monotonic() - t0
    assert dispatch_cost < DELAY / 4, f"offload blocked: {dispatch_cost:.3f}s"
    assert tier.has(101)                      # staged blocks are matchable

    time.sleep(DELAY)                         # "compute" overlaps the copy
    t0 = time.monotonic()
    tier.drain()
    drain_cost = time.monotonic() - t0
    assert drain_cost < DELAY / 4, f"drain re-paid the copy: {drain_cost:.3f}s"

    # correctness survived the overlap
    tier.restore([101], [7])
    np.testing.assert_array_equal(store.data[7], np.full(4, 1, np.float32))


def test_match_and_restore_hit_staged_blocks():
    """A prefix hit landing between dispatch and drain is not lost."""
    store = FakeStore(8)
    tier = KvHostTier(store.gather, store.scatter, capacity_blocks=16)
    alloc = BlockAllocator(8, BS, tier2=tier)
    prompt = list(range(1, 13))
    _, hashes = fill_and_free(alloc, prompt, store)
    # evict A (queues offload), then immediately re-request before any
    # drain: the staged entries must match and restore bit-exact
    big = list(range(100, 100 + 8 * BS))
    blocks_b, _ = alloc.allocate_prompt(big)
    alloc.free_blocks(blocks_b)
    blocks_a2, cached = alloc.allocate_prompt(prompt)
    assert cached == 8  # 2 of 3 complete blocks restorable (cap rule: -1)
    for bid, h in zip(blocks_a2[:2], hashes):
        np.testing.assert_array_equal(
            store.data[bid], np.full(4, h % 251, np.float32)
        )
    alloc.free_blocks(blocks_a2)


def test_fence_commits_staged_offloads():
    store = FakeStore(8)
    tier = KvHostTier(store.gather, store.scatter, capacity_blocks=16)
    alloc = BlockAllocator(8, BS, tier2=tier)
    _, hashes = fill_and_free(alloc, list(range(1, 13)), store)
    blocks_b, _ = alloc.allocate_prompt(list(range(100, 100 + 8 * BS)))
    alloc.free_blocks(blocks_b)
    assert tier.metrics()["host_kv_staged"] > 0
    alloc.fence()
    assert tier.metrics()["host_kv_staged"] == 0
    assert all(tier.has(h) for h in hashes)


def test_host_tier_thrash_keeps_restores_exact():
    """Offload/restore thrash under a small host tier: every restore is
    bit-exact and nothing is double-freed (VERDICT done-bar: zero
    double-use / lost-restore under contention)."""
    store = FakeStore(8)
    tier = KvHostTier(store.gather, store.scatter, capacity_blocks=4)
    alloc = BlockAllocator(8, BS, tier2=tier)
    prompts = {n: list(range(1 + 40 * n, 13 + 40 * n)) for n in range(4)}
    expected = {
        n: compute_block_hashes(p, BS) for n, p in prompts.items()
    }
    rng = np.random.default_rng(3)
    for step in range(120):
        n = int(rng.integers(0, 4))
        prompt = prompts[n]
        blocks, cached = alloc.allocate_prompt(prompt)
        hashes = expected[n]
        # recompute the non-cached suffix (simulating prefill), then
        # verify every restored block carries the right content
        n_restored = cached // BS
        for bid, h in zip(blocks[:n_restored], hashes):
            np.testing.assert_array_equal(
                store.data[bid], np.full(4, h % 251, np.float32),
                err_msg=f"step {step}: lost/corrupt restore of {h}",
            )
        parent = None
        for bid, h in zip(blocks[:3], hashes):
            store.write(bid, np.full(4, h % 251, np.float32))
            alloc.register_complete(bid, h, parent)
            parent = h
        alloc.free_blocks(blocks)
        if step % 7 == 0:
            alloc.fence()
