"""Checkpoint loaders vs. transformers reference logits (CPU, fp32).

One tiny HF checkpoint per family (Mixtral MoE, DeepSeek-V2 MLA,
DeepSeek-V3 sigmoid routing + e_score_correction_bias) is saved with
``save_pretrained`` and loaded through ModelRunner's loader path; prefill
logits must match the transformers forward. Reference analog: the
reference's engines load any HF snapshot (launch/dynamo-run/src/lib.rs:131)
— here the loaders are native (models/loader.py).
"""

import json
import os

import jax
import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.model_runner import ModelRunner

PROMPT = [1, 17, 43, 99, 7, 3, 25, 12, 5, 77, 31, 8]


def _serve_logits(model_dir, hf_cfg, prompt, capacity_factor=8.0):
    """Prefill `prompt` through ModelRunner(model_dir=...) and return the
    per-position logits. Ample MoE capacity so routing never drops."""
    mcfg = ModelConfig.from_hf_config(hf_cfg.to_dict())
    mcfg = ModelConfig(**{
        **{f.name: getattr(mcfg, f.name) for f in mcfg.__dataclass_fields__.values()},
        "moe_capacity_factor": capacity_factor,
        "attention_impl": "xla",
    })
    cfg = EngineConfig(
        model=mcfg, max_batch_size=1, max_model_len=64, kv_block_size=8,
        num_kv_blocks=32, dtype="float32", prefill_buckets=[16],
    )
    runner = ModelRunner(cfg, model_dir=str(model_dir))

    s = 16
    b, bs, w = 1, cfg.kv_block_size, cfg.blocks_per_seq
    tokens = np.zeros((b, s), np.int32)
    tokens[0, : len(prompt)] = prompt
    positions = np.arange(s, dtype=np.int32)[None, :]
    btab = np.zeros((b, w), np.int32)
    btab[0, : s // bs] = np.arange(s // bs)
    slot_map = np.take_along_axis(btab, positions // bs, axis=1) * bs + positions % bs
    slot_map[positions >= len(prompt)] = -1
    ctx = np.full(b, len(prompt), np.int32)

    logits, _ = runner.arch.forward(
        runner.params, mcfg, tokens, positions, runner.kv_cache,
        btab, slot_map, ctx, mesh=runner.mesh,
    )
    return np.asarray(logits)[0, : len(prompt)]


def _hf_logits(model, prompt):
    import torch

    model.eval()
    with torch.no_grad():
        return model(torch.tensor([prompt])).logits[0].numpy()


@pytest.fixture(scope="module")
def mixtral_dir(tmp_path_factory):
    import torch
    from transformers import MixtralConfig, MixtralForCausalLM

    cfg = MixtralConfig(
        vocab_size=256, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=8, num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=64, tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = MixtralForCausalLM(cfg)
    d = tmp_path_factory.mktemp("mixtral")
    model.save_pretrained(d, safe_serialization=True)
    return d, cfg, model


def test_mixtral_loader_matches_hf(mixtral_dir):
    d, cfg, model = mixtral_dir
    got = _serve_logits(d, cfg, PROMPT)
    want = _hf_logits(model, PROMPT)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


@pytest.fixture(scope="module")
def deepseek_v2_dir(tmp_path_factory):
    import torch
    from transformers import DeepseekV2Config, DeepseekV2ForCausalLM

    cfg = DeepseekV2Config(
        vocab_size=256, hidden_size=32, intermediate_size=64,
        moe_intermediate_size=32, num_hidden_layers=3,
        num_attention_heads=4, num_key_value_heads=4,
        n_routed_experts=4, num_experts_per_tok=2, n_shared_experts=1,
        first_k_dense_replace=1, norm_topk_prob=False,
        routed_scaling_factor=1.0, scoring_func="softmax",
        kv_lora_rank=16, q_lora_rank=24, qk_rope_head_dim=8,
        qk_nope_head_dim=16, v_head_dim=16,
        max_position_embeddings=64, tie_word_embeddings=False,
        n_group=1, topk_group=1, topk_method="greedy",
        num_experts_per_token=2,
    )
    torch.manual_seed(1)
    model = DeepseekV2ForCausalLM(cfg)
    d = tmp_path_factory.mktemp("dsv2")
    model.save_pretrained(d, safe_serialization=True)
    return d, cfg, model


def test_deepseek_v2_loader_matches_hf(deepseek_v2_dir):
    d, cfg, model = deepseek_v2_dir
    got = _serve_logits(d, cfg, PROMPT)
    want = _hf_logits(model, PROMPT)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


@pytest.fixture(scope="module")
def deepseek_v3_dir(tmp_path_factory):
    import torch
    from transformers import DeepseekV3Config, DeepseekV3ForCausalLM

    cfg = DeepseekV3Config(
        vocab_size=256, hidden_size=32, intermediate_size=64,
        moe_intermediate_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=4,
        n_routed_experts=4, num_experts_per_tok=2, n_shared_experts=1,
        first_k_dense_replace=1, norm_topk_prob=True,
        routed_scaling_factor=2.5, scoring_func="sigmoid",
        kv_lora_rank=16, q_lora_rank=24, qk_rope_head_dim=8,
        qk_nope_head_dim=16, v_head_dim=16,
        max_position_embeddings=64, tie_word_embeddings=False,
        n_group=1, topk_group=1,
    )
    torch.manual_seed(2)
    model = DeepseekV3ForCausalLM(cfg)
    # e_score_correction_bias inits to zero; make it bite so the test
    # actually checks biased selection + unbiased combine weights
    for layer in model.model.layers[cfg.first_k_dense_replace:]:
        layer.mlp.gate.e_score_correction_bias.data = (
            torch.randn(cfg.n_routed_experts) * 0.5
        )
    d = tmp_path_factory.mktemp("dsv3")
    model.save_pretrained(d, safe_serialization=True)
    return d, cfg, model


def test_deepseek_v3_loader_matches_hf(deepseek_v3_dir):
    d, cfg, model = deepseek_v3_dir
    got = _serve_logits(d, cfg, PROMPT)
    want = _hf_logits(model, PROMPT)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_deepseek_v2_group_limited_matches_hf(tmp_path):
    # V2 "group_limited_greedy": group score = the group's max expert.
    # n_group=2, topk_group=1, top_k=2 forces BOTH selections from the
    # winning group — unrestricted routing would pick a different pair
    # whenever the two best experts straddle groups, so parity here
    # exercises the restriction, not just the plain top-k.
    import torch
    from transformers import DeepseekV2Config, DeepseekV2ForCausalLM

    cfg = DeepseekV2Config(
        vocab_size=256, hidden_size=32, intermediate_size=64,
        moe_intermediate_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=4,
        n_routed_experts=4, num_experts_per_tok=2, n_shared_experts=1,
        first_k_dense_replace=1, norm_topk_prob=False,
        routed_scaling_factor=1.0, scoring_func="softmax",
        kv_lora_rank=16, q_lora_rank=24, qk_rope_head_dim=8,
        qk_nope_head_dim=16, v_head_dim=16,
        max_position_embeddings=64, tie_word_embeddings=False,
        n_group=2, topk_group=1, topk_method="group_limited_greedy",
    )
    torch.manual_seed(3)
    model = DeepseekV2ForCausalLM(cfg)
    d = tmp_path / "dsv2_grouped"
    model.save_pretrained(d, safe_serialization=True)
    got = _serve_logits(d, cfg, PROMPT)
    want = _hf_logits(model, PROMPT)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_deepseek_v3_group_limited_matches_hf(tmp_path):
    # V3 "noaux_tc": group score = sum of the group's top-2 BIASED
    # scores; combine weights stay unbiased. The nonzero correction bias
    # makes selection and combine diverge, and n_group=2/topk_group=1
    # makes the group mask bite (see the V2 variant above).
    import torch
    from transformers import DeepseekV3Config, DeepseekV3ForCausalLM

    cfg = DeepseekV3Config(
        vocab_size=256, hidden_size=32, intermediate_size=64,
        moe_intermediate_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=4,
        n_routed_experts=4, num_experts_per_tok=2, n_shared_experts=1,
        first_k_dense_replace=1, norm_topk_prob=True,
        routed_scaling_factor=2.5, scoring_func="sigmoid",
        kv_lora_rank=16, q_lora_rank=24, qk_rope_head_dim=8,
        qk_nope_head_dim=16, v_head_dim=16,
        max_position_embeddings=64, tie_word_embeddings=False,
        n_group=2, topk_group=1,
    )
    torch.manual_seed(4)
    model = DeepseekV3ForCausalLM(cfg)
    for layer in model.model.layers[cfg.first_k_dense_replace:]:
        layer.mlp.gate.e_score_correction_bias.data = (
            torch.randn(cfg.n_routed_experts) * 0.5
        )
    d = tmp_path / "dsv3_grouped"
    model.save_pretrained(d, safe_serialization=True)
    got = _serve_logits(d, cfg, PROMPT)
    want = _hf_logits(model, PROMPT)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


@pytest.fixture(scope="module")
def qwen2_dir(tmp_path_factory):
    import torch
    from transformers import Qwen2Config, Qwen2ForCausalLM

    # Qwen2 = llama trunk + qkv biases (no attention_bias key in its HF
    # config — the loader infers from the architecture name)
    cfg = Qwen2Config(
        vocab_size=256, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, tie_word_embeddings=False,
        rope_theta=10000.0,
    )
    torch.manual_seed(2)
    model = Qwen2ForCausalLM(cfg)
    d = tmp_path_factory.mktemp("qwen2")
    model.save_pretrained(d, safe_serialization=True)
    return d, cfg, model


def test_qwen2_loader_matches_hf(qwen2_dir):
    """qkv biases load and apply pre-rope — without them the logits are
    garbage, so a tight tolerance proves the bias path end to end."""
    d, cfg, model = qwen2_dir
    got = _serve_logits(d, cfg, PROMPT)
    want = _hf_logits(model, PROMPT)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


@pytest.fixture(scope="module")
def llama3_scaled_dir(tmp_path_factory):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(
        vocab_size=256, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, tie_word_embeddings=False,
        rope_theta=10000.0,
        rope_scaling={
            "rope_type": "llama3", "factor": 8.0,
            "low_freq_factor": 1.0, "high_freq_factor": 4.0,
            "original_max_position_embeddings": 16,
        },
    )
    torch.manual_seed(3)
    model = LlamaForCausalLM(cfg)
    d = tmp_path_factory.mktemp("llama3s")
    model.save_pretrained(d, safe_serialization=True)
    return d, cfg, model


def test_llama3_rope_scaling_matches_hf(llama3_scaled_dir):
    """llama3 rope scaling (Llama-3.1+) matches transformers exactly; the
    tiny original window (16) puts the PROMPT's positions across all
    three scaling bands, so an unscaled implementation diverges."""
    d, cfg, model = llama3_scaled_dir
    got = _serve_logits(d, cfg, PROMPT)
    want = _hf_logits(model, PROMPT)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


@pytest.fixture(scope="module")
def llama_yarn_dir(tmp_path_factory):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(
        vocab_size=256, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, tie_word_embeddings=False,
        rope_theta=10000.0,
        rope_scaling={
            "rope_type": "yarn", "factor": 4.0,
            "original_max_position_embeddings": 16,
            "beta_fast": 32.0, "beta_slow": 1.0,
        },
    )
    torch.manual_seed(4)
    model = LlamaForCausalLM(cfg)
    d = tmp_path_factory.mktemp("llamayarn")
    model.save_pretrained(d, safe_serialization=True)
    return d, cfg, model


def test_yarn_rope_scaling_matches_hf(llama_yarn_dir):
    """yarn frequencies + the mscale attention factor match transformers
    (the tiny original window spreads the prompt across the correction
    range, so both the blend and the cos/sin scaling are exercised)."""
    d, cfg, model = llama_yarn_dir
    got = _serve_logits(d, cfg, PROMPT)
    want = _hf_logits(model, PROMPT)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


@pytest.fixture(scope="module")
def deepseek_yarn_dir(tmp_path_factory):
    import torch
    from transformers import DeepseekV2Config, DeepseekV2ForCausalLM

    cfg = DeepseekV2Config(
        vocab_size=256, hidden_size=32, intermediate_size=64,
        moe_intermediate_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=4,
        n_routed_experts=4, num_experts_per_tok=2, n_shared_experts=1,
        first_k_dense_replace=1, norm_topk_prob=False,
        routed_scaling_factor=1.0, scoring_func="softmax",
        kv_lora_rank=16, q_lora_rank=24, qk_rope_head_dim=8,
        qk_nope_head_dim=16, v_head_dim=16,
        max_position_embeddings=128, tie_word_embeddings=False,
        n_group=1, topk_group=1, topk_method="greedy",
        # mscale_all_dim deliberately ABSENT: transformers' native V2
        # class and DeepSeek's canonical code agree only then (HF V2
        # omits the mscale² softmax adjustment its V3 class applies), so
        # this fixture keeps logits comparable; the canonical softmax
        # scale itself is pinned by test_deepseek_mscale_softmax_scale
        rope_scaling={
            "rope_type": "yarn", "factor": 4.0,
            "original_max_position_embeddings": 16,
            "beta_fast": 32.0, "beta_slow": 1.0,
        },
    )
    torch.manual_seed(5)
    model = DeepseekV2ForCausalLM(cfg)
    d = tmp_path_factory.mktemp("dsyarn")
    model.save_pretrained(d, safe_serialization=True)
    return d, cfg, model


def test_deepseek_yarn_matches_hf(deepseek_yarn_dir):
    """yarn frequency blend + attention factor through the MLA path."""
    d, cfg, model = deepseek_yarn_dir
    got = _serve_logits(d, cfg, PROMPT)
    want = _hf_logits(model, PROMPT)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_deepseek_mscale_softmax_scale():
    """Canonical DeepSeek yarn semantics: mscale_all_dim² multiplies the
    MLA softmax scale (real V2/V3 configs set mscale_all_dim; the
    checkpoints were trained with this — DeepSeek's own modeling code)."""
    import math

    from dynamo_tpu.models.deepseek import mla_softmax_scale

    base = ModelConfig(
        kv_lora_rank=16, qk_nope_head_dim=16, qk_rope_head_dim=8,
        v_head_dim=16,
    )
    assert mla_softmax_scale(base) == pytest.approx(24 ** -0.5)

    scaled = ModelConfig(
        kv_lora_rank=16, qk_nope_head_dim=16, qk_rope_head_dim=8,
        v_head_dim=16,
        rope_scaling={"rope_type": "yarn", "factor": 40.0,
                      "mscale": 1.0, "mscale_all_dim": 1.0},
    )
    m = 0.1 * 1.0 * math.log(40.0) + 1.0
    assert mla_softmax_scale(scaled) == pytest.approx(24 ** -0.5 * m * m)


def test_missing_loader_raises(tmp_path):
    """A checkpoint with no loader for its architecture must raise, not
    silently serve random weights (ADVICE round 1)."""
    from dynamo_tpu.models.loader import load_checkpoint_params

    class FakeArch:
        __name__ = "dynamo_tpu.models.rwkv"

    with pytest.raises(NotImplementedError):
        load_checkpoint_params(str(tmp_path), ModelConfig(), FakeArch, None)


def test_resolve_model_path_local_and_missing(tmp_path, monkeypatch):
    from dynamo_tpu.models.hub import resolve_model_path

    assert resolve_model_path(str(tmp_path)) == str(tmp_path)
    monkeypatch.setenv("HF_HUB_OFFLINE", "1")
    with pytest.raises(FileNotFoundError, match="cannot resolve model"):
        resolve_model_path("no-such-org/no-such-model-xyz")


def test_bf16_checkpoint_stays_2_bytes(tmp_path):
    """bf16 shards load via the ml_dtypes view (no fp32 widening) and
    produce bf16 engine params."""
    import ml_dtypes
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    from dynamo_tpu.models.loader import _iter_safetensors, load_llama_params

    cfg = LlamaConfig(
        vocab_size=64, hidden_size=16, intermediate_size=32,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        tie_word_embeddings=False,
    )
    model = LlamaForCausalLM(cfg).to(torch.bfloat16)
    model.save_pretrained(tmp_path, safe_serialization=True)

    for _, arr in _iter_safetensors(str(tmp_path)):
        assert arr.dtype == ml_dtypes.bfloat16
        assert arr.itemsize == 2
    mcfg = ModelConfig.from_hf_config(cfg.to_dict())
    params = load_llama_params(str(tmp_path), mcfg, dtype="bfloat16")
    assert str(params["layers"]["wq"].dtype) == "bfloat16"


def test_runner_refuses_random_weights_without_flag(tmp_path):
    mcfg = ModelConfig(
        vocab_size=64, hidden_size=16, intermediate_size=32, num_layers=1,
        num_heads=2, num_kv_heads=2,
    )
    cfg = EngineConfig(
        model=mcfg, max_batch_size=1, max_model_len=32, kv_block_size=8,
        num_kv_blocks=8, dtype="float32", prefill_buckets=[16],
    )
    with pytest.raises(FileNotFoundError, match="random weights"):
        ModelRunner(cfg, model_dir=str(tmp_path))


# ---------- FP8 checkpoints (upconvert to bf16 at load) ----------


@pytest.fixture(scope="module")
def fp8_llama_dir(tmp_path_factory):
    """The TINY llama checkpoint re-exported with FP8 projection weights:
    per-output-channel `weight_scale` tensors (compressed-tensors style —
    the format of the reference's canonical benchmark model,
    examples/llm/benchmarks/perf.sh:18 *-FP8-dynamic)."""
    import torch
    from safetensors.torch import save_file
    from transformers import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(
        vocab_size=256, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, tie_word_embeddings=False,
    )
    torch.manual_seed(11)
    model = LlamaForCausalLM(cfg)
    d = tmp_path_factory.mktemp("fp8llama")
    model.save_pretrained(d, safe_serialization=True)  # writes config.json

    quantized = {}
    for name, w in model.state_dict().items():
        if name.endswith("_proj.weight"):
            absmax = w.abs().amax(dim=1, keepdim=True).clamp(min=1e-8)
            scale = (absmax / 448.0).to(torch.float32)
            q = (w / scale).to(torch.float8_e4m3fn)
            quantized[name] = q
            quantized[f"{name}_scale"] = scale[:, 0]
        else:
            quantized[name] = w.contiguous()
    for f in os.listdir(d):
        if f.endswith(".safetensors"):
            os.remove(os.path.join(d, f))
    save_file(quantized, os.path.join(d, "model.safetensors"))
    return d, cfg, model


def test_fp8_checkpoint_loads_and_matches_hf(fp8_llama_dir):
    """An FP8 checkpoint must LOAD (round-2 loader hard-raised) and serve
    logits close to the unquantized model — upconvert error only."""
    d, cfg, model = fp8_llama_dir
    got = _serve_logits(d, cfg, PROMPT)
    want = _hf_logits(model, PROMPT)
    # fp8 e4m3 has ~2 decimal digits; tolerances match quantization noise
    np.testing.assert_allclose(got, want, rtol=0.35, atol=0.35)
    # and the outputs correlate strongly (same model, slightly noisy)
    c = np.corrcoef(got.ravel(), want.ravel())[0, 1]
    assert c > 0.999, c


def test_fp8_block_scale_inv_dequant():
    """DeepSeek-native weight_scale_inv block dequant: fixed 128x128
    blocks, the last block partial (weight_block_size=[128,128])."""
    from dynamo_tpu.models.loader import _dequant_fp8

    arr = np.ones((130, 200), np.float32)
    scale = np.asarray([[2.0, 3.0], [5.0, 7.0]], np.float32)
    out = _dequant_fp8(arr, scale, inverse_blocks=True)
    assert out[0, 0] == 2.0 and out[127, 127] == 2.0
    assert out[0, 128] == 3.0 and out[0, 199] == 3.0
    assert out[128, 0] == 5.0 and out[129, 127] == 5.0
    assert out[129, 199] == 7.0


def test_fp8_per_channel_scale_dequant():
    from dynamo_tpu.models.loader import _dequant_fp8

    arr = np.ones((3, 4), np.float32)
    out = _dequant_fp8(arr, np.asarray([1.0, 2.0, 3.0], np.float32), False)
    np.testing.assert_array_equal(out[:, 0], [1.0, 2.0, 3.0])
    out2 = _dequant_fp8(arr, np.asarray(2.0, np.float32), False)
    assert (out2 == 2.0).all()
