"""Fleet simulator (sim/): replay suite.

The acceptance bar (ISSUE 16): every named scenario completes on CPU in
virtual time driving REAL control-plane instances (SlaPolicy /
AdmissionController / PoolManager / RecoveryController / KvScheduler —
no forks, no mocks of decision logic), the reports carry capacity
curves with at least one scale-up, a shed episode that spares the
highest priority class, and a chaos-triggered drain/respawn whose
flight-event ladder matches the PR 8 e2e pins; and a (scenario, seed)
pair reproduces its report JSON byte-for-byte — which also pins that
nothing under sim/ reads the wall clock.
"""

import asyncio
import json
import os

import pytest

from dynamo_tpu.sim.clock import VirtualClock, run_virtual
from dynamo_tpu.sim.report import render_table
from dynamo_tpu.sim.scenarios import SCENARIOS, run_scenario
from dynamo_tpu.sim.workload import (
    GENERATORS,
    Request,
    load_incident_bundle,
    load_trace_jsonl,
)

# ---------------------------------------------------------------------------
# virtual clock
# ---------------------------------------------------------------------------


def test_virtual_time_runs_fast_and_ordered():
    clock = VirtualClock()
    order = []

    async def sleeper(tag, delay):
        await asyncio.sleep(delay)
        order.append((tag, clock()))

    async def main():
        await asyncio.gather(
            sleeper("c", 3600.0), sleeper("a", 10.0), sleeper("b", 90.0))

    run_virtual(main, clock=clock)
    # timers fire in virtual order and the clock lands on the horizon
    assert [t for t, _ in order] == ["a", "b", "c"]
    assert clock() >= 3600.0
    assert order[0][1] == pytest.approx(10.0, abs=0.5)


def test_virtual_wait_for_times_out_virtually():
    clock = VirtualClock()

    async def main():
        with pytest.raises(asyncio.TimeoutError):
            await asyncio.wait_for(asyncio.Event().wait(), timeout=120.0)

    run_virtual(main, clock=clock)
    assert 120.0 <= clock() < 200.0


# ---------------------------------------------------------------------------
# scenario completions (short horizons; the CLI runs the full ones)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def chaos_report():
    return run_scenario("chaos", seed=0, duration_s=500.0)


def _report_shape_ok(rep):
    assert rep["totals"]["offered"] > 0
    assert rep["capacity"]["curve"], "capacity curve is empty"
    for point in rep["capacity"]["curve"]:
        assert 0.0 <= point["slo_attainment"] <= 1.0
    assert rep["windows"]
    assert isinstance(rep["capacity"]["capacity_qps"], float)
    # renders without crashing, and carries the headline number
    table = render_table(rep)
    assert "capacity=" in table


def test_diurnal_scales_up_and_down():
    rep = run_scenario("diurnal", seed=0, duration_s=900.0)
    _report_shape_ok(rep)
    scale_dirs = [e["direction"] for e in rep["timeline"]
                  if e["kind"] == "scale"]
    assert "up" in scale_dirs, "no scale-up against the diurnal wave"
    assert rep["totals"]["outcomes"].get("completed", 0) > 0
    assert rep["totals"]["slo_attainment"] >= rep["slo_floor"]


def test_diurnal_full_run_scales_aux_pool_to_zero():
    rep = run_scenario("diurnal", seed=0)   # full 1800s horizon
    kinds = {e["kind"] for e in rep["timeline"]}
    assert "scale_to_zero" in kinds
    zero = [e for e in rep["timeline"] if e["kind"] == "scale_to_zero"]
    assert zero[0]["model"] == "sim-aux"


def test_rag_exercises_prefix_reuse_and_cold_tier():
    rep = run_scenario("rag", seed=0, duration_s=420.0)
    _report_shape_ok(rep)
    t = rep["totals"]
    assert t["prefix_hit_tokens"] > 0, "no hot prefix reuse"
    assert t["pulled_blocks"] > 0, "no fabric peer-pull modeled"
    assert t["cold_blocks"] > 0, "no cold-tier rehydration modeled"


def test_prefill_plan_costs_pull_by_backend():
    """The byte model charges a peer pull at the negotiated backend's
    bandwidth (docs/transfer_plane.md): the same pull rides ici at
    ici_pull_gbps / peer_pull_gbps of the DCN cost."""
    from dynamo_tpu.sim.worker import SimRequest, SimWorker, WorkerSpec
    from dynamo_tpu.sim.workload import Request

    spec = WorkerSpec(peer_pull_gbps=40.0, ici_pull_gbps=400.0)
    w = SimWorker("w0", "sim-model", spec, clock=lambda: 0.0)
    sr = SimRequest(Request(arrival_s=0.0, request_id="r0", isl=4096),
                    arrival_t=0.0)
    sr.pulled_blocks = 64
    tcp_s = w._prefill_plan(sr)[0]
    sr.pull_backend = "ici"
    ici_s = w._prefill_plan(sr)[0]
    assert tcp_s > 0.0
    assert ici_s == pytest.approx(tcp_s / 10.0)
    assert sr.pull_transfer_s == pytest.approx(ici_s)


def test_rag_pod_pull_cost_collapses_intra_pod():
    """Same RAG traffic, two fleet shapes: without pods every peer pull
    pays the DCN rate; inside one ICI pod the pulls negotiate the
    collective backend and the per-block transfer cost collapses by the
    bandwidth ratio."""
    dcn = run_scenario("rag", seed=0, duration_s=420.0)
    pod = run_scenario("rag_pod", seed=0, duration_s=420.0)
    _report_shape_ok(pod)
    # no pods → no ici pulls, every pulled block paid the tcp rate
    assert dcn["totals"]["pulled_blocks_ici"] == 0
    assert dcn["totals"]["pulled_blocks"] > 0
    assert dcn["totals"]["pull_transfer_s_tcp"] > 0.0
    # one pod covers the whole fleet → every pull rides the collective
    t = pod["totals"]
    assert t["pulled_blocks"] > 0
    assert t["pulled_blocks_ici"] == t["pulled_blocks"]
    assert t["pull_transfer_s_tcp"] == 0.0
    dcn_per_block = (dcn["totals"]["pull_transfer_s_tcp"]
                     / dcn["totals"]["pulled_blocks"])
    pod_per_block = t["pull_transfer_s_ici"] / t["pulled_blocks"]
    assert pod_per_block < dcn_per_block / 5.0


def test_long_context_routes_sp_prefills():
    rep = run_scenario("long_context", seed=0, duration_s=420.0)
    _report_shape_ok(rep)
    assert rep["totals"]["outcomes"].get("completed", 0) > 0


def test_tenant_spike_quota_sheds_attributed_to_tenant():
    rep = run_scenario("tenant_spike", seed=0, duration_s=500.0)
    _report_shape_ok(rep)
    assert rep["totals"]["outcomes"].get("quota", 0) > 0
    by_tenant = rep["shed_by_tenant"]
    assert by_tenant["burst-tenant"]["shed_rate"] > 0.3
    for tenant in ("acme", "globex"):
        assert by_tenant[tenant]["shed_rate"] < 0.05
    # the zero-replica aux pool cold-started on demand
    assert any(e["kind"] == "cold_start" for e in rep["timeline"])


def test_chaos_shed_episode_spares_highest_priority(chaos_report):
    rep = chaos_report
    shed_outcomes = sum(
        v for k, v in rep["totals"]["outcomes"].items()
        if k not in ("completed", "failed"))
    assert shed_outcomes > 0, "no shed episode during the outage"
    by_prio = rep["shed_by_priority"]
    # the top class rides out the outage that sheds the bottom class
    assert by_prio["0"]["shed_rate"] > by_prio["2"]["shed_rate"]
    assert by_prio["2"]["shed_rate"] < 0.05


def test_chaos_trips_watchdog_drains_and_respawns(chaos_report):
    rep = chaos_report
    kinds = [e["kind"] for e in rep["timeline"]]
    assert kinds.count("watchdog_trip") == 1, "one wedge, one trip"
    assert "chaos" in kinds and "respawn" in kinds
    # the REAL RecoveryController's ladder summary (PR 8 pins)
    assert len(rep["recoveries"]) == 1
    summary = rep["recoveries"][0]
    assert summary["reason"] == "decode_stall"
    assert summary["respawned"] is True
    assert summary["migrated"] == 0          # sim runs migrate=False
    assert summary["failed"] > 0             # in-flight failed over
    # failed-over requests were resubmitted and completed — drain cost
    # shows as resubmits, not request loss
    assert rep["totals"]["resubmits"] >= summary["failed"]
    assert rep["totals"]["outcomes"].get("failed", 0) == 0


def test_chaos_flight_ladder_matches_recovery_e2e_pins(chaos_report):
    """The sim's recovery fires the same flight-event sequence the
    PR 8 chaos e2e pins: drain → per-request failure → respawn."""
    kinds = chaos_report["flight_kinds"]
    assert "recovery.drain" in kinds
    assert "recovery.request_failed" in kinds
    assert "recovery.respawn" in kinds
    d = kinds.index("recovery.drain")
    r = kinds.index("recovery.respawn")
    fails = [i for i, k in enumerate(kinds)
             if k == "recovery.request_failed"]
    assert d < min(fails) and max(fails) < r


# ---------------------------------------------------------------------------
# trace replay
# ---------------------------------------------------------------------------


def _write_trace(path, n=120, start=1700000000.0):
    with open(path, "w", encoding="utf-8") as f:
        for i in range(n):
            f.write(json.dumps({
                "request_id": f"r{i}",
                "time": start + i * 1.5,
                "model": "sim-model",
                "tenant": "t1" if i % 3 else "t2",
                "priority": i % 3,
            }) + "\n")


def test_trace_jsonl_replay(tmp_path):
    path = str(tmp_path / "traces.jsonl")
    _write_trace(path)
    reqs = load_trace_jsonl(path)
    assert len(reqs) == 120
    assert reqs[0].arrival_s == 0.0          # normalized to t=0
    assert all(r.isl > 0 and r.osl > 0 for r in reqs)
    # same file loads to identical sizes (crc32, not salted hash())
    again = load_trace_jsonl(path)
    assert [(r.request_id, r.isl, r.osl) for r in reqs] == \
           [(r.request_id, r.isl, r.osl) for r in again]
    rep = run_scenario("replay", seed=0, requests=reqs)
    assert rep["totals"]["outcomes"].get("completed", 0) == 120


def test_incident_bundle_replay(tmp_path):
    traces = [{"request_id": f"b{i}", "time": 500.0 + i * 2.0,
               "isl": 300 + i, "osl": 40}
              for i in range(40)]
    (tmp_path / "traces.json").write_text(json.dumps(traces))
    reqs = load_incident_bundle(str(tmp_path))
    assert len(reqs) == 40
    assert reqs[0].isl == 300                # explicit sizes honored
    rep = run_scenario("replay", seed=0, requests=reqs,
                       duration_s=300.0)
    assert rep["totals"]["outcomes"].get("completed", 0) == 40


def test_replay_scenario_requires_a_trace():
    with pytest.raises(ValueError):
        run_scenario("replay", seed=0)


# ---------------------------------------------------------------------------
# determinism: same (scenario, seed) → byte-identical report JSON
# ---------------------------------------------------------------------------


def test_report_byte_identical_same_seed(chaos_report):
    again = run_scenario("chaos", seed=0, duration_s=500.0)
    assert json.dumps(chaos_report, sort_keys=True) == \
           json.dumps(again, sort_keys=True)


def test_report_differs_across_seeds():
    a = run_scenario("tenant_spike", seed=1, duration_s=300.0)
    b = run_scenario("tenant_spike", seed=2, duration_s=300.0)
    assert json.dumps(a, sort_keys=True) != json.dumps(b, sort_keys=True)


def test_no_wall_clock_reads_in_sim_package():
    """Determinism depends on virtual time only: nothing under sim/ may
    consult the wall clock. Enforced by the dynlint ``wallclock-in-sim``
    rule (which replaced this test's original regex scan — the rule
    resolves import aliases, knows call sites from strings in comments,
    and supports per-line suppressions); this pin keeps the sim package
    at ZERO findings so new wall-clock reads fail here, not just in the
    lint step."""
    from dynamo_tpu.analysis.core import lint_paths
    from dynamo_tpu.analysis.rules import get_rules

    sim_dir = os.path.join(
        os.path.dirname(__file__), "..", "dynamo_tpu", "sim")
    findings = lint_paths([sim_dir], get_rules(["wallclock-in-sim"]))
    assert findings == [], [f.render() for f in findings]


# ---------------------------------------------------------------------------
# CLI: capacity gate semantics
# ---------------------------------------------------------------------------


def test_cli_exits_nonzero_when_slo_floor_violated(tmp_path, capsys):
    import scripts.fleetsim as fleetsim
    out = str(tmp_path / "report.json")
    # an unattainable floor turns the run into a failing capacity gate
    rc = fleetsim.main([
        "--scenario", "chaos", "--duration", "400",
        "--slo-floor", "1.01", "--json-out", out,
    ])
    assert rc == 2
    report = json.loads((tmp_path / "report.json").read_text())
    assert report["slo_floor"] == 1.01
    capsys.readouterr()


def test_cli_lists_scenarios_and_passes_gate(tmp_path, capsys):
    import scripts.fleetsim as fleetsim
    assert fleetsim.main(["--list"]) == 0
    listing = capsys.readouterr().out
    for name in SCENARIOS:
        assert name in listing
    metrics = str(tmp_path / "metrics.prom")
    rc = fleetsim.main([
        "--scenario", "long_context", "--duration", "300",
        "--metrics-out", metrics,
    ])
    assert rc == 0
    exposition = (tmp_path / "metrics.prom").read_text()
    # the run is observable through the standard /metrics plumbing
    assert "dynamo_sim_requests_total" in exposition
    assert "dynamo_sim_virtual_time_seconds" in exposition
    assert "dynamo_planner_admissions_total" in exposition
    capsys.readouterr()


# ---------------------------------------------------------------------------
# generators
# ---------------------------------------------------------------------------


def test_generators_are_seed_deterministic():
    import random
    for name, gen in GENERATORS.items():
        a = gen(random.Random(7), duration_s=120.0)
        b = gen(random.Random(7), duration_s=120.0)
        assert [(r.request_id, r.arrival_s, r.isl, r.osl) for r in a] == \
               [(r.request_id, r.arrival_s, r.isl, r.osl) for r in b], name
        assert all(0.0 <= r.arrival_s < 120.0 for r in a), name


def test_rag_generator_emits_shared_prefix_groups():
    import random
    reqs = GENERATORS["rag"](random.Random(0), duration_s=120.0)
    groups = {r.prefix_group for r in reqs}
    assert len(groups) > 1
    assert all(r.prefix_tokens > 0 for r in reqs)
