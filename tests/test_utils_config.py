"""Env/TOML layered config + structured logging (utils/config, utils/logging).

Reference analog: lib/runtime/src/config.rs (Figment layering with
DYN_* env on top, empty vars ignored) and logging.rs (DYN_LOG filters,
DYN_LOGGING_JSONL)."""

import dataclasses
import io
import json
import logging

import pytest

from dynamo_tpu.utils.config import RuntimeSettings, from_settings
from dynamo_tpu.utils.logging import (
    JsonlFormatter,
    parse_filter,
    setup_logging,
    stage_summary,
)


@dataclasses.dataclass
class _Cfg:
    workers: int = 4
    rate: float = 1.5
    debug: bool = False
    name: str = "default"


def test_defaults_when_nothing_set(tmp_path):
    cfg = from_settings(_Cfg, "TEST_X_", config_files=())
    assert cfg == _Cfg()


def test_toml_layer_then_env_wins(tmp_path, monkeypatch):
    toml = tmp_path / "conf.toml"
    toml.write_text('workers = 8\nname = "from-toml"\nunknown_key = 1\n')
    cfg = from_settings(_Cfg, "TEST_X_", config_files=(str(toml),))
    assert cfg.workers == 8 and cfg.name == "from-toml"

    monkeypatch.setenv("TEST_X_WORKERS", "16")
    monkeypatch.setenv("TEST_X_DEBUG", "true")
    monkeypatch.setenv("TEST_X_RATE", "2.25")
    monkeypatch.setenv("TEST_X_NAME", "")  # empty == unset (reference semantics)
    cfg = from_settings(_Cfg, "TEST_X_", config_files=(str(toml),))
    assert cfg.workers == 16
    assert cfg.debug is True
    assert cfg.rate == 2.25
    assert cfg.name == "from-toml"


def test_dyn_config_path_env(tmp_path, monkeypatch):
    toml = tmp_path / "site.toml"
    toml.write_text("workers = 32\n")
    monkeypatch.setenv("DYN_CONFIG_PATH", str(toml))
    cfg = from_settings(_Cfg, "TEST_X_", config_files=())
    assert cfg.workers == 32


def test_runtime_settings_env(monkeypatch):
    monkeypatch.setenv("DYN_RUNTIME_NUM_WORKER_THREADS", "3")
    monkeypatch.setenv("DYN_WORKER_GRACEFUL_SHUTDOWN_TIMEOUT", "7.5")
    s = RuntimeSettings.from_settings()
    assert s.num_worker_threads == 3
    assert s.graceful_shutdown_timeout == 7.5


def test_parse_filter_spec():
    root, per = parse_filter("warn,dynamo_tpu.engine=debug,aiohttp=error")
    assert root == logging.WARNING
    assert per == {"dynamo_tpu.engine": logging.DEBUG, "aiohttp": logging.ERROR}


def test_setup_logging_jsonl(monkeypatch):
    monkeypatch.setenv("DYN_LOGGING_JSONL", "1")
    monkeypatch.setenv("DYN_LOG", "info,quiet.mod=error")
    buf = io.StringIO()
    setup_logging(stream=buf)
    try:
        logging.getLogger("test.target").info(
            "hello %s", "world", extra={"request_id": "r1"}
        )
        logging.getLogger("quiet.mod").info("suppressed")
        lines = [l for l in buf.getvalue().splitlines() if l]
        assert len(lines) == 1
        rec = json.loads(lines[0])
        assert rec["level"] == "INFO"
        assert rec["target"] == "test.target"
        assert rec["message"] == "hello world"
        assert rec["request_id"] == "r1"
        assert "time" in rec
    finally:
        logging.getLogger().handlers[:] = []
        logging.getLogger("quiet.mod").setLevel(logging.NOTSET)


def test_stage_summary():
    # deltas attribute to the mark that CLOSES each gap (marks record
    # phase completions); the tail to now is "egress"
    stages = [("http", 1.0), ("preprocess", 1.010), ("generate", 1.025)]
    s = stage_summary(stages)
    assert s.startswith("preprocess=10.0ms generate=15.0ms egress=")
    assert stage_summary([]) == ""


def test_context_add_stage():
    from dynamo_tpu.runtime.engine import Context

    ctx = Context({"x": 1})
    ctx.add_stage("http")
    mapped = ctx.map({"y": 2})
    mapped.add_stage("preprocess")
    # stages survive map() — shared baggage
    assert [s for s, _ in ctx.stages] == ["http", "preprocess"]
