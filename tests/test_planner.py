"""Closed-loop SLA planner: deterministic policy simulations + actuation.

Scripted metric feeds through an injectable clock → pinned action
sequences (hysteresis, cooldown, bounds), then the full loop: planner
step → KubeActuator → Reconciler → InMemoryKube replica patch, and the
local actuation paths (disagg router config, admission knobs,
api-store record scaling).
"""

import asyncio

import pytest

from dynamo_tpu.deploy import InMemoryKube, Reconciler
from dynamo_tpu.planner import (
    AdmissionAction,
    AdmissionConfig,
    AdmissionController,
    KubeActuator,
    LocalActuator,
    Planner,
    PolicyConfig,
    RebalanceAction,
    ScaleAction,
    SignalStore,
    SlaPolicy,
    StoreScaleActuator,
)
from dynamo_tpu.telemetry.flight import FlightRecorder


class Clock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def make_policy(clock, **overrides):
    defaults = dict(
        window_s=10.0,
        prefill_queue_wait_up_s=1.0,
        prefill_queue_wait_down_s=0.1,
        prefill_queue_depth_up=4.0,
        decode_busy_up=0.9,
        decode_busy_down=0.3,
        decode_waiting_up=4.0,
        min_replicas=1,
        max_replicas=3,
        scale_up_cooldown_s=30.0,
        scale_down_cooldown_s=120.0,
        rebalance_cooldown_s=30.0,
        shed_step_cooldown_s=5.0,
        relax_after_clear_s=30.0,
    )
    defaults.update(overrides)
    return SlaPolicy(PolicyConfig(**defaults), clock=clock)


# --------------------------------------------------------------------------
# SignalStore
# --------------------------------------------------------------------------


def test_signal_store_window_aggregates():
    clock = Clock()
    store = SignalStore(window_s=100.0, clock=clock)
    for i in range(5):
        store.observe("x", float(i), t=float(i))
    clock.t = 4.0
    assert store.latest("x") == 4.0
    assert store.mean("x") == 2.0
    assert store.mean("x", window_s=2.0) == pytest.approx(3.0)  # t>=2: 2,3,4
    assert store.max("x", window_s=2.0) == 4.0
    assert store.delta("x") == 4.0
    assert store.age("x") == 0.0
    assert store.latest("missing", default=7.0) == 7.0
    assert store.mean("missing") is None


def test_signal_store_prunes_old_samples():
    clock = Clock()
    store = SignalStore(window_s=10.0, clock=clock)
    store.observe("x", 1.0, t=0.0)
    clock.t = 20.0
    store.observe("x", 2.0)
    # the t=0 sample fell out of the window entirely
    assert store.mean("x") == 2.0
    assert store.delta("x") == 0.0  # single sample left


def test_signal_store_observe_many_skips_non_numeric():
    store = SignalStore(clock=Clock())
    store.observe_many({"a": 1, "b": "text", "c": True, "d": 2.5})
    assert store.latest("a") == 1.0
    assert store.latest("d") == 2.5
    assert store.latest("b") is None and store.latest("c") is None


# --------------------------------------------------------------------------
# policy: scale with hysteresis, cooldown, bounds
# --------------------------------------------------------------------------


def test_prefill_scale_up_sequence_with_cooldown_and_max():
    clock = Clock()
    signals = SignalStore(clock=clock)
    policy = make_policy(clock)
    replicas = {"prefill": 1, "decode": 1}

    signals.observe("prefill.queue_wait_s", 2.0)
    (a,) = policy.decide(signals, replicas)
    assert isinstance(a, ScaleAction)
    assert (a.role, a.current_replicas, a.target_replicas) == ("prefill", 1, 2)
    assert a.direction == "up"
    replicas["prefill"] = 2

    # still hot 5s later: cooldown holds the second step back
    clock.advance(5.0)
    signals.observe("prefill.queue_wait_s", 2.0)
    assert policy.decide(signals, replicas) == []

    # past the cooldown: next step lands
    clock.advance(30.0)
    signals.observe("prefill.queue_wait_s", 2.0)
    (a2,) = policy.decide(signals, replicas)
    assert (a2.current_replicas, a2.target_replicas) == (2, 3)
    replicas["prefill"] = 3

    # at max_replicas: no action, and no cooldown burned
    clock.advance(31.0)
    signals.observe("prefill.queue_wait_s", 2.0)
    assert policy.decide(signals, replicas) == []


def test_prefill_hysteresis_dead_zone_and_scale_down():
    clock = Clock()
    signals = SignalStore(clock=clock)
    policy = make_policy(clock)
    replicas = {"prefill": 2}

    # between the down (0.1) and up (1.0) thresholds: nothing moves
    signals.observe("prefill.queue_wait_s", 0.5)
    signals.observe("prefill.queue_depth", 0.0)
    assert policy.decide(signals, replicas) == []

    # idle → scale down (advance past the window so the dead-zone
    # sample no longer drags the mean above the down threshold)
    clock.advance(11.0)
    signals.observe("prefill.queue_wait_s", 0.01)
    signals.observe("prefill.queue_depth", 0.0)
    (a,) = policy.decide(signals, replicas)
    assert isinstance(a, ScaleAction)
    assert (a.direction, a.target_replicas) == ("down", 1)
    replicas["prefill"] = 1

    # min_replicas floor: no further down even after the long cooldown
    clock.advance(121.0)
    signals.observe("prefill.queue_wait_s", 0.01)
    signals.observe("prefill.queue_depth", 0.0)
    assert policy.decide(signals, replicas) == []


def test_scale_down_waits_for_its_longer_cooldown():
    clock = Clock()
    signals = SignalStore(clock=clock)
    policy = make_policy(clock)
    replicas = {"decode": 2}

    signals.observe("decode.slot_busy_ratio", 0.95)
    (up,) = policy.decide(signals, replicas)
    assert up.direction == "up"
    replicas["decode"] = 3

    # load vanishes immediately; up-cooldown (30s) has passed but the
    # down-cooldown (120s) has not — the capacity is kept
    clock.advance(40.0)
    signals.observe("decode.slot_busy_ratio", 0.0)
    signals.observe("decode.waiting", 0.0)
    assert policy.decide(signals, replicas) == []

    clock.advance(120.0)
    signals.observe("decode.slot_busy_ratio", 0.0)
    (down,) = policy.decide(signals, replicas)
    assert down.direction == "down" and down.target_replicas == 2


def test_unknown_role_never_scales():
    clock = Clock()
    signals = SignalStore(clock=clock)
    policy = make_policy(clock)
    signals.observe("prefill.queue_wait_s", 5.0)
    assert policy.decide(signals, {}) == []  # role not deployed


# --------------------------------------------------------------------------
# policy: disagg rebalance
# --------------------------------------------------------------------------


def test_rebalance_moves_threshold_both_ways_with_cooldown():
    clock = Clock()
    signals = SignalStore(clock=clock)
    policy = make_policy(clock)
    assert policy.local_prefill_length == 1000

    # prefill queue backed up, decode has headroom → keep more local
    signals.observe("prefill.queue_depth", 5.0)
    signals.observe("decode.slot_busy_ratio", 0.5)
    actions = policy.decide(signals, {})
    (reb,) = [a for a in actions if isinstance(a, RebalanceAction)]
    assert reb.max_local_prefill_length == 2000
    assert policy.local_prefill_length == 2000

    # cooldown: same pressure, no second move
    clock.advance(5.0)
    signals.observe("prefill.queue_depth", 5.0)
    signals.observe("decode.slot_busy_ratio", 0.5)
    assert [a for a in policy.decide(signals, {})
            if isinstance(a, RebalanceAction)] == []

    # decode saturated, queue drained → send more remote (back down)
    clock.advance(31.0)
    signals.observe("prefill.queue_depth", 0.0)
    signals.observe("decode.slot_busy_ratio", 0.95)
    actions = policy.decide(signals, {})
    (reb2,) = [a for a in actions if isinstance(a, RebalanceAction)]
    assert reb2.max_local_prefill_length == 1000


def test_rebalance_clamps_to_bounds():
    clock = Clock()
    signals = SignalStore(clock=clock)
    policy = make_policy(clock, max_local_prefill_length=1500)
    signals.observe("prefill.queue_depth", 5.0)
    signals.observe("decode.slot_busy_ratio", 0.5)
    (reb,) = [a for a in policy.decide(signals, {})
              if isinstance(a, RebalanceAction)]
    assert reb.max_local_prefill_length == 1500  # clamped, not 2000


# --------------------------------------------------------------------------
# policy: admission shed/relax ladder
# --------------------------------------------------------------------------


def test_admission_shed_ladder_and_relax():
    clock = Clock()
    signals = SignalStore(clock=clock)
    policy = make_policy(clock)

    # watchdog trip counter moves → saturated → shed level 1
    signals.observe("watchdog.trips", 0.0)
    clock.advance(1.0)
    signals.observe("watchdog.trips", 1.0)
    (a,) = policy.decide(signals, {})
    assert isinstance(a, AdmissionAction) and a.shed_level == 1

    # still saturated inside the step cooldown: hold
    clock.advance(1.0)
    signals.observe("watchdog.trips", 2.0)
    assert policy.decide(signals, {}) == []

    # past the step cooldown and still tripping: level 2 (the max —
    # the highest class is never shed)
    clock.advance(6.0)
    signals.observe("watchdog.trips", 3.0)
    (a2,) = policy.decide(signals, {})
    assert a2.shed_level == 2
    clock.advance(6.0)
    signals.observe("watchdog.trips", 4.0)
    assert policy.decide(signals, {}) == []  # capped

    # trips stop; once the window slides past them the plane reads clear
    clock.advance(15.0)  # old trip samples age out of the 10s window
    signals.observe("watchdog.trips", 4.0)
    assert policy.decide(signals, {}) == []  # first clear pass only arms
    clock.advance(31.0)  # relax_after_clear_s elapsed
    signals.observe("watchdog.trips", 4.0)
    (r1,) = policy.decide(signals, {})
    assert isinstance(r1, AdmissionAction) and r1.shed_level == 1
    clock.advance(31.0)
    signals.observe("watchdog.trips", 4.0)
    (r2,) = policy.decide(signals, {})
    assert r2.shed_level == 0


def test_admission_sheds_on_kv_and_busy_saturation():
    clock = Clock()
    signals = SignalStore(clock=clock)
    policy = make_policy(clock, saturation_kv_usage=0.95,
                         saturation_busy=0.95, saturation_waiting=3.0)
    signals.observe("kv.usage_ratio", 0.99)
    (a,) = policy.decide(signals, {})
    assert isinstance(a, AdmissionAction) and "kv usage" in a.reason

    policy2 = make_policy(clock, saturation_busy=0.95, saturation_waiting=3.0)
    signals2 = SignalStore(clock=clock)
    signals2.observe("decode.slot_busy_ratio", 0.99)
    signals2.observe("decode.waiting", 5.0)
    actions = policy2.decide(signals2, {})
    sheds = [a for a in actions if isinstance(a, AdmissionAction)]
    assert len(sheds) == 1 and sheds[0].shed_level == 1


# --------------------------------------------------------------------------
# planner loop → actuators
# --------------------------------------------------------------------------


def _cr(prefill=1, decode=1):
    return {
        "apiVersion": "dynamo.tpu/v1alpha1",
        "kind": "DynamoTpuGraphDeployment",
        "metadata": {"name": "g1", "namespace": "serving", "uid": "u-1"},
        "spec": {
            "image": "dynamo-tpu:test",
            "namespace": "public",
            "services": {
                "prefill": {"role": "prefill", "replicas": prefill,
                            "modelPath": "/m"},
                "decode": {"role": "decode", "replicas": decode,
                           "modelPath": "/m"},
            },
        },
    }


@pytest.mark.asyncio
async def test_planner_scale_up_lands_in_inmemory_kube():
    clock = Clock()
    kube = InMemoryKube()
    actuator = KubeActuator(Reconciler(kube), _cr())
    flight = FlightRecorder(64)
    planner = Planner(
        policy=make_policy(clock),
        sources=[lambda: {"prefill.queue_wait_s": 3.0,
                          "prefill.queue_depth": 6.0}],
        actuators=[actuator],
        flight=flight,
        clock=clock,
    )
    actions = await planner.step()
    scale = [a for a in actions if isinstance(a, ScaleAction)]
    assert scale and scale[0].role == "prefill"
    dep = kube.objects["Deployment/serving/g1-prefill"]
    assert dep["spec"]["replicas"] == 2
    assert planner.actions_applied  # audit trail
    # the actuator reports the patched CR's replica map back to policy
    assert actuator.replicas() == {"prefill": 2, "decode": 1}
    # decision is auditable: metric + flight event
    text = planner.registry.render()
    assert ('dynamo_planner_replica_target_replicas{role="prefill"} 2'
            in text)
    assert 'kind="scale_up"' in text
    kinds = [e["kind"] for e in flight.snapshot()]
    assert "planner.action" in kinds

    # second cycle inside the cooldown: no further patch
    clock.advance(1.0)
    await planner.step()
    assert kube.objects["Deployment/serving/g1-prefill"]["spec"]["replicas"] == 2


@pytest.mark.asyncio
async def test_planner_survives_broken_source_and_actuator():
    clock = Clock()

    class ExplodingActuator:
        async def apply(self, action):
            raise RuntimeError("boom")

    planner = Planner(
        policy=make_policy(clock),
        sources=[lambda: 1 / 0,
                 lambda: {"prefill.queue_wait_s": 3.0}],
        actuators=[ExplodingActuator()],
        replicas=lambda: {"prefill": 1},
        flight=FlightRecorder(16),
        clock=clock,
    )
    actions = await planner.step()  # must not raise
    assert [a for a in actions if isinstance(a, ScaleAction)]
    assert planner.actions_applied == []  # nothing claimed the action
    assert 'applied="false"' in planner.registry.render()


@pytest.mark.asyncio
async def test_local_actuator_rebalances_router_and_admission():
    from dynamo_tpu.disagg.router import DisaggRouter

    router = DisaggRouter(max_local_prefill_length=1000,
                          max_prefill_queue_size=2)
    admission = AdmissionController(
        AdmissionConfig(limit=4), flight=FlightRecorder(16))
    actuator = LocalActuator(disagg_router=router, admission=admission)

    assert await actuator.apply(RebalanceAction(
        max_local_prefill_length=2000, max_prefill_queue_size=3, reason="t"))
    assert router.max_local_prefill_length == 2000
    assert router.max_prefill_queue_size == 3

    assert await actuator.apply(AdmissionAction(
        shed_level=1, limit=8, reason="t"))
    assert admission.shed_level == 1 and admission.limit == 8
    # limit=None leaves the configured limit alone
    assert await actuator.apply(AdmissionAction(
        shed_level=0, limit=None, reason="t"))
    assert admission.shed_level == 0 and admission.limit == 8

    # an unhandled action type is declined, not swallowed
    assert not await actuator.apply(ScaleAction(
        role="decode", target_replicas=2, current_replicas=1, reason="t"))


@pytest.mark.asyncio
async def test_store_scale_actuator_patches_record():
    class FakeStore:
        def __init__(self):
            self.rec = {"name": "g1", "spec": {
                "services": {"decode": {"role": "decode", "replicas": 1}}}}

        def get(self, name):
            return self.rec if name == "g1" else None

        def update(self, name, spec):
            self.rec = {"name": name, "spec": spec}

    store = FakeStore()
    actuator = StoreScaleActuator(store, "g1")
    assert await actuator.apply(ScaleAction(
        role="decode", target_replicas=3, current_replicas=1, reason="t"))
    assert store.rec["spec"]["services"]["decode"]["replicas"] == 3
    assert await actuator.replicas() == {"decode": 3}
    # unknown deployment: declined, no crash
    missing = StoreScaleActuator(store, "nope")
    assert not await missing.apply(ScaleAction(
        role="decode", target_replicas=2, current_replicas=1, reason="t"))


@pytest.mark.asyncio
async def test_planner_loop_runs_and_stops():
    clock = Clock()
    planner = Planner(
        policy=make_policy(clock),
        sources=[lambda: {"prefill.queue_wait_s": 0.0}],
        flight=FlightRecorder(16),
        clock=clock,
    )
    planner.config.interval_s = 0.01
    planner.start()
    await asyncio.sleep(0.05)
    planner.stop()
    assert planner._task is None
    text = planner.registry.render()
    assert "dynamo_planner_cycles_total" in text


# --------------------------------------------------------------------------
# review hardening regressions
# --------------------------------------------------------------------------


def test_prefill_scales_up_on_depth_alone():
    """The standalone planner often has ONLY the queue-depth poll (the
    wait histogram lives on the workers) — depth must be an independent
    trigger, not AND-gated on a signal that never arrives."""
    clock = Clock()
    signals = SignalStore(clock=clock)
    policy = make_policy(clock)
    signals.observe("prefill.queue_depth", 10.0)
    (a,) = policy.decide(signals, {"prefill": 1})
    assert isinstance(a, ScaleAction)
    assert (a.role, a.direction) == ("prefill", "up")


def test_saturation_from_admission_signals_alone():
    """Pure-frontend planner (in=http out=none --planner): the edge's
    own state — deep admission queue at full concurrency — must read as
    saturation even with no engine/aggregator signal wired."""
    clock = Clock()
    signals = SignalStore(clock=clock)
    policy = make_policy(clock, saturation_admission_queue=4.0)
    signals.observe("admission.queue_depth", 8.0)
    signals.observe("admission.inflight_ratio", 1.0)
    (a,) = policy.decide(signals, {})
    assert isinstance(a, AdmissionAction) and a.shed_level == 1
    assert "admission queue" in a.reason


def test_signal_latest_goes_blind_past_the_window():
    """A source that stopped reporting must not serve its last value
    forever — the policy should skip a dead signal, not act on it."""
    clock = Clock()
    store = SignalStore(window_s=10.0, clock=clock)
    store.observe("prefill.queue_depth", 7.0)
    assert store.latest("prefill.queue_depth") == 7.0
    clock.advance(11.0)
    assert store.latest("prefill.queue_depth") is None
    assert store.latest("prefill.queue_depth", 0.0) == 0.0


@pytest.mark.asyncio
async def test_unapplied_action_rolls_back_policy_state():
    """An action no actuator claims must not drift the policy's pacing
    state: the shed level stays where reality is, and the decision
    retries next cycle instead of silently relaxing later."""
    clock = Clock()
    policy = make_policy(clock)
    planner = Planner(
        policy=policy,
        sources=[lambda: {"kv.usage_ratio": 0.99}],
        actuators=[],  # nobody to apply the shed
        flight=FlightRecorder(16),
        clock=clock,
    )
    actions = await planner.step()
    assert any(isinstance(a, AdmissionAction) for a in actions)
    assert policy.shed_level == 0  # rolled back — nothing actually shed
    clock.advance(6.0)
    actions2 = await planner.step()  # retried, not escalated
    sheds = [a for a in actions2 if isinstance(a, AdmissionAction)]
    assert sheds and sheds[0].shed_level == 1
    assert policy.shed_level == 0  # still unapplied, still rolled back
