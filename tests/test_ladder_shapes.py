"""Flagship-shape validation without flagship hardware (VERDICT r3
weak #5): every BASELINE ladder rung's model is traced at its REAL
dimensions via ``jax.eval_shape`` (no buffers allocated), and the 70B
TP step is lowered with real Megatron shardings over an 8-device mesh.

Tiny-shape tests can hide bugs that only appear at real dims (reshape
factorizations, head/expert divisibility, cache layout padding, >2**31
element counts); abstract evaluation catches those for free.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.model_runner import build_mesh
from dynamo_tpu.models import resolve

# BASELINE.md ladder rungs at their true public dimensions
LADDER = {
    "llama3-8b": ModelConfig(
        vocab_size=128256, hidden_size=4096, intermediate_size=14336,
        num_layers=32, num_heads=32, num_kv_heads=8, head_dim=128,
        rope_theta=500000.0,
    ),
    "llama3-70b": ModelConfig(
        vocab_size=128256, hidden_size=8192, intermediate_size=28672,
        num_layers=80, num_heads=64, num_kv_heads=8, head_dim=128,
        rope_theta=500000.0,
    ),
    "mixtral-8x7b": ModelConfig(
        vocab_size=32000, hidden_size=4096, intermediate_size=14336,
        num_layers=32, num_heads=32, num_kv_heads=8, head_dim=128,
        num_experts=8, num_experts_per_tok=2,
    ),
    "deepseek-r1": ModelConfig(
        vocab_size=129280, hidden_size=7168, intermediate_size=18432,
        num_layers=61, num_heads=128, num_kv_heads=128, head_dim=128,
        kv_lora_rank=512, q_lora_rank=1536,
        qk_nope_head_dim=128, qk_rope_head_dim=64, v_head_dim=128,
        num_experts=256, num_experts_per_tok=8,
        moe_intermediate_size=2048, first_k_dense_replace=3,
        n_shared_experts=1,
        # the real checkpoint's routing semantics (config.json):
        # sigmoid scoring + noaux_tc group-limited top-k over 8 groups
        moe_scoring_func="sigmoid", norm_topk_prob=True,
        routed_scaling_factor=2.5, n_group=8, topk_group=4,
    ),
    "gpt-oss-20b": ModelConfig(
        vocab_size=201088, hidden_size=2880, intermediate_size=2880,
        num_layers=24, num_heads=64, num_kv_heads=8, head_dim=64,
        model_family="gptoss", num_experts=32, num_experts_per_tok=4,
        sliding_window=128, attention_bias=True, rope_theta=150000.0,
        rope_scaling={"rope_type": "yarn", "factor": 32.0,
                      "beta_fast": 32.0, "beta_slow": 1.0,
                      "original_max_position_embeddings": 4096},
    ),
}

# public parameter counts (within tolerance: embeddings/norm details)
EXPECTED_PARAMS = {
    "llama3-8b": 8.0e9,
    "llama3-70b": 70.6e9,
    "mixtral-8x7b": 46.7e9,
    "deepseek-r1": 671e9,
    "gpt-oss-20b": 20.9e9,
}


def _tree_params(shapes) -> int:
    return sum(int(np.prod(leaf.shape)) for leaf in jax.tree.leaves(shapes))


@pytest.mark.parametrize("name", sorted(LADDER))
def test_ladder_model_traces_at_real_dims(name):
    cfg = LADDER[name]
    cfg.attention_impl = "xla"
    arch = resolve(cfg)
    num_blocks, bs = 2048, 16

    param_shapes = jax.eval_shape(
        lambda key: arch.init_params(cfg, key, jnp.bfloat16),
        jax.random.PRNGKey(0),
    )
    total = _tree_params(param_shapes)
    want = EXPECTED_PARAMS[name]
    assert abs(total - want) / want < 0.10, (
        f"{name}: param count {total / 1e9:.1f}B vs expected "
        f"{want / 1e9:.1f}B — the real-dims config is wrong"
    )

    cache_shapes = jax.eval_shape(
        lambda: arch.init_kv_cache(cfg, num_blocks, bs, jnp.bfloat16)
    )

    def run(params, cache, tokens, positions, btab, slots, ctx):
        logits, cache = arch.forward(
            params, cfg, tokens, positions, cache, btab, slots, ctx,
        )
        return logits

    # decode step at serving batch; prefill chunk at a real bucket
    for b, s in ((16, 1), (1, 512)):
        w = 8192 // bs
        out = jax.eval_shape(
            run,
            param_shapes, cache_shapes,
            jax.ShapeDtypeStruct((b, s), jnp.int32),
            jax.ShapeDtypeStruct((b, s), jnp.int32),
            jax.ShapeDtypeStruct((b, w), jnp.int32),
            jax.ShapeDtypeStruct((b, s), jnp.int32),
            jax.ShapeDtypeStruct((b,), jnp.int32),
        )
        assert out.shape == (b, s, cfg.vocab_size)
        assert out.dtype == jnp.bfloat16


def test_llama70b_tp8_step_lowers_with_real_shardings():
    """The 70B decode step LOWERS (trace + StableHLO, still no buffers)
    with the production tp=8 Megatron shardings on an 8-device mesh —
    catches spec/rank/divisibility errors GSPMD would reject."""
    cfg = LADDER["llama3-70b"]
    cfg.attention_impl = "xla"
    arch = resolve(cfg)
    mesh = build_mesh(1, 8, jax.devices()[:8])
    num_blocks, bs = 2048, 16

    param_shapes = jax.eval_shape(
        lambda key: arch.init_params(cfg, key, jnp.bfloat16),
        jax.random.PRNGKey(0),
    )
    pspecs = arch.param_specs(param_shapes)
    sharded_params = jax.tree.map(
        lambda sds, spec: jax.ShapeDtypeStruct(
            sds.shape, sds.dtype,
            sharding=NamedSharding(mesh, spec),
        ),
        param_shapes, pspecs,
        is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct),
    )
    cache_shapes = jax.tree.map(
        lambda sds: jax.ShapeDtypeStruct(
            sds.shape, sds.dtype,
            sharding=NamedSharding(mesh, P(None, None, None, "tp", None)),
        ),
        jax.eval_shape(
            lambda: arch.init_kv_cache(cfg, num_blocks, bs, jnp.bfloat16)
        ),
    )

    b, s, w = 16, 1, 8192 // bs

    def run(params, cache, tokens, positions, btab, slots, ctx):
        logits, cache = arch.forward(
            params, cfg, tokens, positions, cache, btab, slots, ctx,
            mesh=mesh,
        )
        return logits, cache

    lowered = jax.jit(run).lower(
        sharded_params, cache_shapes,
        jax.ShapeDtypeStruct((b, s), jnp.int32),
        jax.ShapeDtypeStruct((b, s), jnp.int32),
        jax.ShapeDtypeStruct((b, w), jnp.int32),
        jax.ShapeDtypeStruct((b, s), jnp.int32),
        jax.ShapeDtypeStruct((b,), jnp.int32),
    )
    text = lowered.as_text()
    assert "stablehlo" in text or "mhlo" in text or "module" in text


def test_deepseek_r1_hf_config_parses():
    """The north-star rung (BASELINE configs[4], DeepSeek-R1 671B): the
    REAL config.json keys — group-limited routing included, the round-4
    blocker — parse into a servable ModelConfig."""
    cfg = ModelConfig.from_hf_config({
        "architectures": ["DeepseekV3ForCausalLM"],
        "vocab_size": 129280, "hidden_size": 7168,
        "intermediate_size": 18432, "num_hidden_layers": 61,
        "num_attention_heads": 128, "num_key_value_heads": 128,
        "kv_lora_rank": 512, "q_lora_rank": 1536,
        "qk_nope_head_dim": 128, "qk_rope_head_dim": 64,
        "v_head_dim": 128,
        "n_routed_experts": 256, "num_experts_per_tok": 8,
        "moe_intermediate_size": 2048, "first_k_dense_replace": 3,
        "n_shared_experts": 1,
        "scoring_func": "sigmoid", "norm_topk_prob": True,
        "routed_scaling_factor": 2.5, "n_group": 8, "topk_group": 4,
        "topk_method": "noaux_tc",
        "rope_theta": 10000.0, "max_position_embeddings": 163840,
        "rms_norm_eps": 1e-6,
    })
    # the parsed config must MATCH the ladder's trace config — one
    # source of truth for what "DeepSeek-R1 at real dims" means
    want = LADDER["deepseek-r1"]
    for field in ("vocab_size", "hidden_size", "intermediate_size",
                  "num_layers", "num_heads", "kv_lora_rank",
                  "q_lora_rank", "qk_nope_head_dim", "qk_rope_head_dim",
                  "v_head_dim", "num_experts", "num_experts_per_tok",
                  "moe_intermediate_size", "first_k_dense_replace",
                  "n_shared_experts", "moe_scoring_func",
                  "norm_topk_prob", "routed_scaling_factor", "n_group",
                  "topk_group"):
        assert getattr(cfg, field) == getattr(want, field), field
