"""Stall-watchdog contract (telemetry/watchdog.py).

No-false-positive half: an idle engine (empty queue) and a legitimately
long prefill/decode (slow-but-progressing host syncs, healthy remote
waits) must NOT trip. Detection half: a fake-runner decode loop
artificially wedged mid-burst (the host sync never returns — the
executor-side shape of a hung Mosaic compile or dead device) MUST trip
within the configured deadline, and the dumped artifact must carry the
wedged request's last flight events, all-thread stacks, the active
request table, and a metrics snapshot — on disk AND at
``GET /debug/flight``.
"""

import asyncio
import json
import os
import threading
import uuid

import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.scheduler import EngineRequest, Scheduler
from dynamo_tpu.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.engine import AsyncEngineContext
from dynamo_tpu.telemetry.flight import FlightRecorder
from dynamo_tpu.telemetry.watchdog import StallWatchdog

from test_decode_pipeline import FakeRunner


# --------------------------------------------------------------------------
# probe-level unit contract
# --------------------------------------------------------------------------


def _probe(heartbeat=None, steps=0, depth=0, remote=0, active=0,
           stopping=False):
    import time

    hb = heartbeat if heartbeat is not None else time.monotonic()
    return {
        "heartbeat_t": hb, "steps": steps, "queue_depth": depth,
        "pending_remote": remote, "active": active, "stopping": stopping,
    }


def _run_watchdog(probe_fn, cycles=8, interval=0.03, stall=0.1, **kw):
    async def go():
        wd = StallWatchdog(
            probe_fn, interval_s=interval, stall_s=stall,
            flight=FlightRecorder(), **kw,
        ).start()
        await asyncio.sleep(interval * cycles + stall)
        await wd.stop()
        return wd

    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(go())
    finally:
        loop.close()


def test_idle_engine_with_stale_heartbeat_never_trips():
    # an idle loop parks on wake.wait(): heartbeat arbitrarily old, but
    # with NO pending work that is rest, not a stall
    wd = _run_watchdog(lambda: _probe(heartbeat=0.0))
    assert wd.trips == []
    assert wd.loop_lag_s < 1.0  # lag gauge sampled, loop healthy


def test_healthy_remote_prefill_wait_never_trips_no_throughput():
    # pending remote prefills poll on a fresh heartbeat with frozen
    # steps: the remote deadline machinery owns that wait, not us
    wd = _run_watchdog(lambda: _probe(steps=7, remote=3))
    assert wd.trips == []


def test_stale_heartbeat_with_pending_work_trips_decode_stall_once():
    wd = _run_watchdog(lambda: _probe(heartbeat=0.0, active=1, steps=4),
                       cycles=16)
    # edge-triggered: one persistent wedge = ONE trip, not one per cycle
    assert [t["reason"] for t in wd.trips] == ["decode_stall"]
    text = wd.registry.render()
    assert ('dynamo_watchdog_trips_total{reason="decode_stall"} 1.0'
            in text)
    assert "dynamo_runtime_event_loop_lag_seconds" in text
    # the trip landed in the flight ring too
    assert any(e["kind"] == "watchdog.trip" for e in wd.flight.snapshot())


def test_frozen_steps_with_queued_work_trips_no_throughput():
    # fresh heartbeat (the loop spins) but the dispatch counter never
    # moves while requests queue: starved admission
    wd = _run_watchdog(lambda: _probe(steps=42, depth=2), cycles=16)
    assert [t["reason"] for t in wd.trips] == ["no_throughput"]


def test_idle_gap_then_arrival_does_not_instantly_trip_no_throughput():
    """Steps frozen through a long idle period, then work arrives: the
    starvation clock must restart at arrival (it re-stamps while the
    queue is empty) — only a queue that STAYS starved past the deadline
    trips."""
    state = {"depth": 0}

    def probe():
        return _probe(steps=10, depth=state["depth"])

    async def go():
        wd = StallWatchdog(probe, interval_s=0.03, stall_s=0.15,
                           flight=FlightRecorder()).start()
        await asyncio.sleep(0.5)   # idle far beyond stall_s, steps frozen
        state["depth"] = 2         # burst of work arrives
        await asyncio.sleep(0.09)  # well under stall_s since arrival
        early = list(wd.trips)
        await asyncio.sleep(0.5)   # now genuinely starved
        await wd.stop()
        return early, list(wd.trips)

    loop = asyncio.new_event_loop()
    try:
        early, late = loop.run_until_complete(go())
    finally:
        loop.close()
    assert early == [], "tripped instantly on arrival after an idle gap"
    assert [t["reason"] for t in late] == ["no_throughput"]


def test_advancing_steps_never_trip():
    counter = {"steps": 0}

    def probe():
        counter["steps"] += 1  # every sample sees progress
        return _probe(steps=counter["steps"], depth=2, active=1)

    wd = _run_watchdog(probe, cycles=16)
    assert wd.trips == []


def test_stopping_engine_never_trips():
    wd = _run_watchdog(lambda: _probe(heartbeat=0.0, active=3,
                                      stopping=True))
    assert wd.trips == []


def test_flaky_probe_does_not_kill_the_watchdog():
    calls = {"n": 0}

    def probe():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("scrape race")
        return _probe(heartbeat=0.0, active=1)

    wd = _run_watchdog(probe, cycles=16)
    assert calls["n"] > 3  # survived the failures and kept sampling
    assert [t["reason"] for t in wd.trips] == ["decode_stall"]


# --------------------------------------------------------------------------
# scheduler-level: no false positives on real (fake-runner) engines
# --------------------------------------------------------------------------


class _SlowArray:
    """Device-array stand-in whose host sync takes ``delay`` seconds —
    runs in the scheduler's executor, so the loop stays free (the shape
    of a legitimately slow device)."""

    def __init__(self, arr, delay):
        self._arr = np.asarray(arr)
        self._delay = delay

    def __array__(self, dtype=None):
        import time

        time.sleep(self._delay)
        a = self._arr
        return a.astype(dtype) if dtype is not None else a

    def __getitem__(self, item):
        return _SlowArray(self._arr[item], self._delay)


class _WedgeableRunner(FakeRunner):
    """FakeRunner whose decode host-syncs can be slowed or wedged.

    ``sync_delay`` makes every decode sync take that long (legitimately
    slow). ``wedge_after`` wedges the Nth decode burst's sync on an
    Event that only the test releases — the executor-side shape of a
    hung compile / dead device, mid-burst."""

    def __init__(self, config, sync_delay=0.0, wedge_after=None):
        super().__init__(config)
        self.sync_delay = sync_delay
        self.wedge_after = wedge_after
        self.release = threading.Event()
        self.wedged = threading.Event()  # test observability

    def decode_burst(self, *args, **kw):
        out = super().decode_burst(*args, **kw)
        if (self.wedge_after is not None
                and self.burst_calls > self.wedge_after):
            runner = self

            class _Wedged(_SlowArray):
                def __array__(self, dtype=None):
                    runner.wedged.set()
                    runner.release.wait()
                    return super().__array__(dtype)

            return tuple(_Wedged(a, 0.0) for a in out)
        if self.sync_delay:
            return tuple(_SlowArray(a, self.sync_delay) for a in out)
        return out


def _request(prompt, max_tokens):
    req = PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(max_tokens=max_tokens,
                                       ignore_eos=True),
        sampling_options=SamplingOptions(temperature=0.0),
        eos_token_ids=[],
    )
    return EngineRequest(
        request_id=uuid.uuid4().hex, prompt=list(prompt), req=req,
        ctx=AsyncEngineContext(), out_queue=asyncio.Queue(),
    )


def _config(**kw):
    kw.setdefault("num_kv_blocks", 64)
    kw.setdefault("max_model_len", 256)
    # fused bursts: idle-runner decode rides decode_burst, which is the
    # seam _WedgeableRunner slows/wedges
    kw.setdefault("multi_step_decode", 4)
    return EngineConfig(
        model=ModelConfig(vocab_size=512, hidden_size=32,
                          intermediate_size=64, num_layers=1, num_heads=2,
                          num_kv_heads=1),
        max_batch_size=4, kv_block_size=8, dtype="float32",
        enable_prefix_caching=False, **kw,
    )


async def _collect(er):
    toks = []
    while True:
        out = await er.out_queue.get()
        if out is None:
            return toks
        toks.extend(out.token_ids)


def test_idle_scheduler_never_trips():
    config = _config()

    async def go():
        runner = FakeRunner(config)
        sched = Scheduler(runner, config, flight=FlightRecorder())
        sched.start()
        wd = StallWatchdog(
            probe=sched.watchdog_probe, requests=sched.request_table,
            flight=sched.flight, interval_s=0.02, stall_s=0.1,
        ).start()
        await asyncio.sleep(0.5)  # way past the deadline, zero work
        trips = list(wd.trips)
        await wd.stop()
        await sched.stop()
        return trips

    loop = asyncio.new_event_loop()
    try:
        assert loop.run_until_complete(go()) == []
    finally:
        loop.close()


def test_long_prefill_and_slow_decode_do_not_trip():
    """Work that takes many times the stall deadline overall — a long
    chunked prefill + per-pass decode syncs slower than the sampling
    interval — must not trip: every pass re-stamps the heartbeat and
    advances the step counter."""
    # 120-token prompt at <=16 computed tokens/step: 8+ prefill chunks
    config = _config(max_prefill_tokens_per_step=16,
                     prefill_buckets=[16, 32, 64, 128, 256])

    async def go():
        runner = _WedgeableRunner(config, sync_delay=0.05)
        sched = Scheduler(runner, config, flight=FlightRecorder())
        sched.start()
        wd = StallWatchdog(
            probe=sched.watchdog_probe, requests=sched.request_table,
            flight=sched.flight, interval_s=0.02, stall_s=0.25,
        ).start()
        er = _request(list(range(1, 121)), 12)
        sched.add_request(er)
        toks = await _collect(er)  # total runtime >> stall_s
        trips = list(wd.trips)
        await wd.stop()
        await sched.stop()
        return toks, trips

    loop = asyncio.new_event_loop()
    try:
        toks, trips = loop.run_until_complete(go())
    finally:
        loop.close()
    assert len(toks) == 12
    assert trips == []


# --------------------------------------------------------------------------
# the wedge: trip + artifact, end to end (disk AND /debug/flight)
# --------------------------------------------------------------------------


def _drive_wedged_engine(tmp_path, stall_s=0.25):
    """Start a fake engine, wedge its 3rd decode burst mid-sync, let the
    watchdog trip, and return (trip list, artifact path, wedged request,
    scheduler, service port artifacts...). Shared by the disk and HTTP
    assertions."""
    config = _config()
    dump_dir = os.path.join(str(tmp_path), "flight")
    out = {}

    async def go():
        import aiohttp

        from dynamo_tpu.http.service import HttpService, ModelManager

        runner = _WedgeableRunner(config, wedge_after=2)
        flight = FlightRecorder()
        sched = Scheduler(runner, config, flight=flight)
        sched.start()
        wd = StallWatchdog(
            probe=sched.watchdog_probe, requests=sched.request_table,
            registry=sched.registry, flight=flight,
            interval_s=0.02, stall_s=stall_s, dump_dir=dump_dir,
        ).start()
        service = HttpService(ModelManager(), host="127.0.0.1", port=0)
        await service.start()

        er = _request([1, 17, 43], 64)
        sched.add_request(er)
        collector = asyncio.ensure_future(_collect(er))
        try:
            # the runner wedges its 3rd burst; the watchdog must trip
            # within its deadline + a few sampling intervals
            for _ in range(200):
                if wd.trips:
                    break
                await asyncio.sleep(0.05)
            out["trips"] = list(wd.trips)
            out["wedged"] = runner.wedged.is_set()
            out["request_id"] = er.request_id

            # the on-demand endpoint, while still wedged
            async with aiohttp.ClientSession() as s:
                async with s.get(
                    f"http://127.0.0.1:{service.port}/debug/flight"
                ) as r:
                    out["http_status"] = r.status
                    out["http_artifact"] = await r.json()
        finally:
            runner.release.set()  # un-wedge so everything drains
            await collector
            await wd.stop()
            await service.stop()
            await sched.stop()

    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(go())
    finally:
        loop.close()
    return out, dump_dir


def test_wedged_decode_trips_and_dumps_artifact(tmp_path):
    out, dump_dir = _drive_wedged_engine(tmp_path)
    assert out["wedged"], "test is vacuous: the runner never wedged"
    reasons = [t["reason"] for t in out["trips"]]
    assert "decode_stall" in reasons, reasons
    rid = out["request_id"]

    # --- on-disk artifact ---
    files = sorted(os.listdir(dump_dir))
    assert files, "trip produced no artifact"
    with open(os.path.join(dump_dir, files[0])) as f:
        artifact = json.load(f)
    assert artifact["reason"] == "decode_stall"
    # the wedged request's last flight events are present
    mine = [e for e in artifact["events"] if e.get("request_id") == rid]
    assert any(e["kind"] == "scheduler.admission" for e in mine)
    dispatches = [
        e for e in artifact["events"]
        if e["kind"] == "scheduler.burst_dispatch"
        and rid in (e.get("data") or {}).get("requests", [])
    ]
    assert dispatches, "no dispatch event for the wedged request"
    # all-thread stacks include the executor thread stuck in the sync
    stacks = "\n".join(
        ln for th in artifact["threads"] for ln in th["stack"]
    )
    assert "__array__" in stacks
    # active request table names the wedged request as decoding
    table = artifact["sources"][0]["requests"]
    assert any(r["request_id"] == rid and r["state"] == "decoding"
               for r in table)
    # metrics snapshot rode along, including the trip counter itself
    metrics = artifact["sources"][0]["metrics"]
    assert "dynamo_watchdog_trips_total" in metrics
    assert "dynamo_scheduler_step_duration_seconds" in metrics

    # --- GET /debug/flight, served while wedged ---
    assert out["http_status"] == 200
    http_art = out["http_artifact"]
    assert any(e.get("request_id") == rid for e in http_art["events"])
    assert any("__array__" in ln for th in http_art["threads"]
               for ln in th["stack"])
    assert any(
        r["request_id"] == rid
        for src in http_art["sources"] for r in (src["requests"] or [])
    )


def test_wedge_recovers_cleanly_after_release(tmp_path):
    """After the wedge clears, the stream completes and the watchdog
    re-arms (condition cleared) without further trips."""
    out, _ = _drive_wedged_engine(tmp_path)
    # exactly one decode_stall for one persistent wedge
    assert [t["reason"] for t in out["trips"]].count("decode_stall") == 1
