"""Generic object pool (utils/pool.py — reference utils/pool.rs analog)."""

import asyncio
import gc

import pytest

from dynamo_tpu.utils.pool import Pool, PoolExhausted


async def test_acquire_release_cycle_and_reset_hook():
    resets = []
    pool = Pool(["a", "b"], on_return=resets.append)
    assert pool.available == 2 and pool.capacity == 2
    async with await pool.acquire() as v1:
        assert v1 == "a" and pool.available == 1
        item2 = pool.try_acquire()
        assert item2.value == "b" and pool.available == 0
        with pytest.raises(PoolExhausted):
            pool.try_acquire()
        item2.release()
        assert pool.available == 1
    assert pool.available == 2
    assert resets == ["b", "a"]
    # double release is a no-op; using a returned item raises
    item = pool.try_acquire()
    item.release()
    item.release()
    with pytest.raises(RuntimeError):
        _ = item.value
    assert pool.available == 2


async def test_acquire_waits_for_return():
    pool = Pool([1])
    item = await pool.acquire()
    got = []

    async def waiter():
        got.append((await pool.acquire()).value)

    task = asyncio.create_task(waiter())
    await asyncio.sleep(0.01)
    assert not got                      # blocked: pool empty
    item.release()
    await asyncio.wait_for(task, 5)
    assert got == [1]
    # the hand-off went straight to the waiter, not through the deque,
    # and the waiter's item still returns normally
    assert pool.available == 1

    with pytest.raises(PoolExhausted):
        i = await pool.acquire()
        try:
            await pool.acquire(timeout=0.05)
        finally:
            i.release()


async def test_leaked_item_returns_at_gc():
    pool = Pool(["x"])
    item = await pool.acquire()
    assert pool.available == 0
    del item                             # leaked without release
    gc.collect()
    assert pool.available == 1           # the finalizer returned it


async def test_shared_items_return_on_last_release():
    pool = Pool(["v"])
    shared = (await pool.acquire()).share()
    clone = shared.share()
    assert shared.strong_count == 2
    assert shared.value == clone.value == "v"
    shared.release()
    assert pool.available == 0           # clone still holds it
    clone.release()
    assert pool.available == 1
    with pytest.raises(RuntimeError):
        _ = clone.value


async def test_cancelled_waiter_does_not_lose_the_item():
    """A release can hand the value to a waiter's future in the same
    tick its cancellation fires — the value must be recovered, not
    silently drained from the pool."""
    pool = Pool(["conn"])
    holder = await pool.acquire()

    async def waiter():
        await pool.acquire()

    task = asyncio.create_task(waiter())
    await asyncio.sleep(0.01)           # waiter parked on its future
    holder.release()                    # hand-off resolves the future...
    task.cancel()                       # ...and the cancel lands first
    with pytest.raises(asyncio.CancelledError):
        await task
    assert pool.available == 1, "cancelled hand-off drained the pool"
    # and a plain timeout near a hand-off also recovers
    h2 = await pool.acquire()
    t2 = asyncio.create_task(pool.acquire(timeout=0.02))
    await asyncio.sleep(0.05)
    h2.release()
    with pytest.raises(PoolExhausted):
        await t2
    assert pool.available == 1


async def test_leaked_shared_item_returns_at_gc():
    pool = Pool(["v"])
    shared = (await pool.acquire()).share()
    clone = shared.share()
    shared.release()
    del shared, clone                   # last handle leaked, not released
    gc.collect()
    assert pool.available == 1, "leaked shared item shrank the pool"


async def test_concurrent_churn_preserves_capacity():
    pool = Pool(list(range(4)))
    seen = set()

    async def worker(n):
        for _ in range(25):
            async with await pool.acquire() as v:
                seen.add(v)
                await asyncio.sleep(0)

    await asyncio.gather(*(worker(i) for i in range(16)))
    assert pool.available == 4
    assert seen == {0, 1, 2, 3}
