"""bench.py outage fallback: the banked number IS the reported value.

The driver captures bench.py's single JSON line as BENCH_r{N}.json — the
record of truth for the round. When the shared compile relay is wedged at
capture time (rounds 2 and 4), every live attempt times out; the fallback
must then report the round's banked real-hardware measurement as
``value`` (annotated ``banked: true`` with provenance), not 0.0.
"""

import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_banked_fallback_reports_real_number():
    bench = _load_bench()
    result = bench.banked_fallback()
    # the repo ships bench_levers_r04.json with headline 1737.5 tok/s;
    # a simulated total outage must surface it as the value
    assert result["banked"] is True
    assert result["value"] > 0.0
    assert result["vs_baseline"] > 0.0
    assert result["unit"] == "tokens/s"
    assert "error" in result  # still honest that live attempts failed
    src = result["banked_from"]
    assert src["file"].startswith("examples/llm/benchmarks/results/")
    assert src["tokens_per_s"] == result["value"]
    # one-line JSON-serializable (the driver parses a single line)
    line = json.dumps(result)
    assert "\n" not in line and json.loads(line) == result


def test_banked_fallback_prefers_newest_round(tmp_path):
    bench = _load_bench()
    results_dir = tmp_path / "examples" / "llm" / "benchmarks" / "results"
    results_dir.mkdir(parents=True)
    (results_dir / "bench_levers_r02.json").write_text(json.dumps(
        {"headline": {"tokens_per_s": 100.0, "vs_baseline": 0.1}}))
    (results_dir / "bench_levers_r10.json").write_text(json.dumps(
        {"headline": {"tokens_per_s": 900.0, "vs_baseline": 0.9},
         "measured_utc": "2026-07-31T01:09:00Z"}))
    # r11 exists but has no usable headline → skipped, r10 wins (not r02)
    (results_dir / "bench_levers_r11.json").write_text(json.dumps(
        {"headline": {"tokens_per_s": 0.0}}))
    result = bench.banked_fallback(repo_root=str(tmp_path))
    assert result["value"] == 900.0
    assert result["banked_from"]["measured"] == "2026-07-31T01:09:00Z"


def test_no_banked_file_reports_zero(tmp_path):
    bench = _load_bench()
    result = bench.banked_fallback(repo_root=str(tmp_path))
    assert result["value"] == 0.0
    assert "banked" not in result
