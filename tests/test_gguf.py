"""GGUF parsing: round-trip against an in-test writer, MDC/config mapping."""

import struct

import pytest

from dynamo_tpu.engine.config import ModelConfig
from dynamo_tpu.llm.gguf import (
    GgufError,
    mdc_from_gguf,
    model_config_from_gguf,
    read_gguf,
)

T_UINT32, T_FLOAT32, T_BOOL, T_STRING, T_ARRAY, T_UINT64 = 4, 6, 7, 8, 9, 10


def _s(text: str) -> bytes:
    raw = text.encode()
    return struct.pack("<Q", len(raw)) + raw


def _kv(key: str, vtype: int, payload: bytes) -> bytes:
    return _s(key) + struct.pack("<I", vtype) + payload


def write_gguf(path, metadata_blobs, tensors=(), version=3):
    """Minimal GGUF writer (header + metadata + tensor descriptors)."""
    with open(path, "wb") as f:
        f.write(b"GGUF")
        f.write(struct.pack("<I", version))
        f.write(struct.pack("<Q", len(tensors)))
        f.write(struct.pack("<Q", len(metadata_blobs)))
        for blob in metadata_blobs:
            f.write(blob)
        for name, shape, ggml_type, offset in tensors:
            f.write(_s(name))
            f.write(struct.pack("<I", len(shape)))
            for d in shape:
                f.write(struct.pack("<Q", d))
            f.write(struct.pack("<I", ggml_type))
            f.write(struct.pack("<Q", offset))


@pytest.fixture
def gguf_path(tmp_path):
    path = tmp_path / "tiny.gguf"
    tokens = [_s(t) for t in ("<s>", "</s>", "hello", "world")]
    meta = [
        _kv("general.architecture", T_STRING, _s("llama")),
        _kv("general.name", T_STRING, _s("tiny-llama")),
        _kv("llama.context_length", T_UINT32, struct.pack("<I", 2048)),
        _kv("llama.embedding_length", T_UINT32, struct.pack("<I", 64)),
        _kv("llama.block_count", T_UINT32, struct.pack("<I", 2)),
        _kv("llama.feed_forward_length", T_UINT32, struct.pack("<I", 128)),
        _kv("llama.attention.head_count", T_UINT32, struct.pack("<I", 8)),
        _kv("llama.attention.head_count_kv", T_UINT32, struct.pack("<I", 4)),
        _kv("llama.rope.freq_base", T_FLOAT32, struct.pack("<f", 500000.0)),
        _kv("tokenizer.ggml.bos_token_id", T_UINT32, struct.pack("<I", 0)),
        _kv("tokenizer.ggml.eos_token_id", T_UINT32, struct.pack("<I", 1)),
        _kv("tokenizer.chat_template", T_STRING, _s("{{ messages }}")),
        _kv("tokenizer.ggml.tokens", T_ARRAY,
            struct.pack("<I", T_STRING) + struct.pack("<Q", len(tokens)) + b"".join(tokens)),
        _kv("some.flag", T_BOOL, struct.pack("<B", 1)),
        _kv("some.big", T_UINT64, struct.pack("<Q", 1 << 40)),
    ]
    tensors = [
        ("token_embd.weight", (64, 4), 0, 0),
        ("blk.0.attn_q.weight", (64, 64), 30, 1024),  # bf16
    ]
    write_gguf(path, meta, tensors)
    return str(path)


def test_read_gguf_roundtrip(gguf_path):
    g = read_gguf(gguf_path)
    assert g.version == 3
    assert g.architecture == "llama"
    assert g.metadata["llama.context_length"] == 2048
    assert g.metadata["some.flag"] is True
    assert g.metadata["some.big"] == 1 << 40
    assert g.metadata["tokenizer.ggml.tokens"] == ["<s>", "</s>", "hello", "world"]
    assert g.arch_key("embedding_length") == 64
    assert [t.name for t in g.tensors] == ["token_embd.weight", "blk.0.attn_q.weight"]
    assert g.tensors[1].type_name == "bf16"
    assert g.tensors[0].shape == (64, 4)


def test_model_config_from_gguf(gguf_path):
    cfg = model_config_from_gguf(read_gguf(gguf_path))
    assert isinstance(cfg, ModelConfig)
    assert cfg.vocab_size == 4          # from token list length
    assert cfg.hidden_size == 64
    assert cfg.num_layers == 2
    assert cfg.num_heads == 8 and cfg.num_kv_heads == 4
    assert cfg.rope_theta == 500000.0
    assert cfg.max_position_embeddings == 2048


def test_mdc_from_gguf(gguf_path):
    mdc = mdc_from_gguf(gguf_path)
    assert mdc.display_name == "tiny-llama"
    assert mdc.context_length == 2048
    assert mdc.bos_token_id == 0
    assert mdc.eos_token_ids == [1]
    assert mdc.chat_template == "{{ messages }}"
    # config is HF-shaped so engine_config_from_mdc rebuilds the same
    # ModelConfig a snapshot-backed worker would
    assert mdc.config["hidden_size"] == 64
    assert mdc.config["num_hidden_layers"] == 2
    assert mdc.config["num_attention_heads"] == 8


def test_rejects_non_gguf(tmp_path):
    bad = tmp_path / "bad.bin"
    bad.write_bytes(b"NOPE" + b"\x00" * 64)
    with pytest.raises(GgufError, match="not a GGUF"):
        read_gguf(str(bad))


def test_rejects_v1(tmp_path):
    path = tmp_path / "v1.gguf"
    path.write_bytes(b"GGUF" + struct.pack("<I", 1) + b"\x00" * 16)
    with pytest.raises(GgufError, match="version 1"):
        read_gguf(str(path))


def test_truncated_file(tmp_path):
    path = tmp_path / "trunc.gguf"
    path.write_bytes(b"GGUF" + struct.pack("<I", 3) + struct.pack("<Q", 0)
                     + struct.pack("<Q", 5))  # promises 5 kvs, has none
    with pytest.raises(GgufError, match="truncated|implausible"):
        read_gguf(str(path))


# ---------- tokenizer reconstruction (tokenizer.ggml.* -> Tokenizer) ----------


def _tok_array(strings):
    return (
        struct.pack("<I", T_STRING)
        + struct.pack("<Q", len(strings))
        + b"".join(_s(t) for t in strings)
    )


def _i32_array(vals):
    T_INT32 = 5
    return (
        struct.pack("<I", T_INT32)
        + struct.pack("<Q", len(vals))
        + b"".join(struct.pack("<i", v) for v in vals)
    )


def _f32_array(vals):
    return (
        struct.pack("<I", T_FLOAT32)
        + struct.pack("<Q", len(vals))
        + b"".join(struct.pack("<f", v) for v in vals)
    )


def test_gguf_bpe_tokenizer_matches_original(tmp_path):
    """A byte-level-BPE vocab shipped inside GGUF reconstructs to a
    tokenizer that encodes identically to the original."""
    import json as _json

    from fixtures import build_tiny_tokenizer

    from dynamo_tpu.llm.gguf import tokenizer_from_gguf
    from dynamo_tpu.llm.tokenizer import HFTokenizer

    orig = build_tiny_tokenizer()
    spec = _json.loads(orig.to_str())
    vocab = spec["model"]["vocab"]
    tokens = [t for t, _ in sorted(vocab.items(), key=lambda kv: kv[1])]
    merges = [
        m if isinstance(m, str) else " ".join(m) for m in spec["model"]["merges"]
    ]
    types = [3 if t.startswith("<") and t.endswith(">") else 1 for t in tokens]

    path = tmp_path / "bpe.gguf"
    write_gguf(path, [
        _kv("general.architecture", T_STRING, _s("llama")),
        _kv("tokenizer.ggml.model", T_STRING, _s("gpt2")),
        _kv("tokenizer.ggml.tokens", T_ARRAY, _tok_array(tokens)),
        _kv("tokenizer.ggml.merges", T_ARRAY, _tok_array(merges)),
        _kv("tokenizer.ggml.token_type", T_ARRAY, _i32_array(types)),
    ])

    rebuilt = tokenizer_from_gguf(read_gguf(str(path)))
    for text in ("hello world", "the user asks a question", "a b c"):
        assert rebuilt.encode(text, add_special_tokens=False).ids == \
            orig.encode(text, add_special_tokens=False).ids
        assert rebuilt.decode(rebuilt.encode(text).ids) == \
            orig.decode(orig.encode(text).ids)

    # end-to-end path: HFTokenizer.from_model_path on a .gguf
    wrapped = HFTokenizer.from_model_path(str(path))
    assert wrapped.decode(wrapped.encode("hello world")) == orig.decode(
        orig.encode("hello world", add_special_tokens=False).ids
    )


def test_gguf_unigram_tokenizer_roundtrip(tmp_path):
    """SentencePiece-style (model='llama') vocab: encode/decode round-trips."""
    from dynamo_tpu.llm.gguf import tokenizer_from_gguf

    tokens = ["<unk>", "<s>", "</s>", "▁hello", "▁world", "▁",
              "h", "e", "l", "o", "w", "r", "d"]
    scores = [0.0, 0.0, 0.0, -1.0, -1.0, -2.0,
              -5.0, -5.0, -5.0, -5.0, -5.0, -5.0, -5.0]
    types = [2, 3, 3] + [1] * 10

    path = tmp_path / "spm.gguf"
    write_gguf(path, [
        _kv("general.architecture", T_STRING, _s("llama")),
        _kv("tokenizer.ggml.model", T_STRING, _s("llama")),
        _kv("tokenizer.ggml.tokens", T_ARRAY, _tok_array(tokens)),
        _kv("tokenizer.ggml.scores", T_ARRAY, _f32_array(scores)),
        _kv("tokenizer.ggml.token_type", T_ARRAY, _i32_array(types)),
        _kv("tokenizer.ggml.unknown_token_id", T_UINT32, struct.pack("<I", 0)),
    ])
    tok = tokenizer_from_gguf(read_gguf(str(path)))
    ids = tok.encode("hello world", add_special_tokens=False).ids
    assert ids[0] == tokens.index("▁hello")
    assert ids[1] == tokens.index("▁world")
    assert tok.decode(ids) == "hello world"


def test_gguf_rejects_implausible_array_count(tmp_path):
    """Corrupt array counts fail fast instead of exhausting memory."""
    path = tmp_path / "bad.gguf"
    blob = (
        _s("tokenizer.ggml.tokens")
        + struct.pack("<I", T_ARRAY)
        + struct.pack("<I", T_STRING)
        + struct.pack("<Q", 1 << 50)   # claims 2^50 elements
    )
    write_gguf(path, [blob])
    with pytest.raises(GgufError, match="implausible"):
        read_gguf(str(path))
