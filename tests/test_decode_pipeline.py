"""Dispatch-ahead decode pipeline (EngineConfig.decode_pipeline_depth=2)
edge cases, driven by a deterministic fake runner.

The fake models exactly the carry semantics the pipeline relies on —
``next = f(prev_token, position)`` — so the synchronous and pipelined
schedulers must produce byte-identical streams through every edge:
finishes detected one burst late, preemption forcing a drain, and the
guided/spec/``n>1`` fallbacks. The real-model differential lives in
tests/test_multi_step.py; this file isolates the SCHEDULER's pipeline
logic from the numerics.
"""

import asyncio
import uuid

import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.scheduler import EngineRequest, Scheduler
from dynamo_tpu.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.engine import AsyncEngineContext


class FakeRunner:
    """Deterministic stand-in for ModelRunner.

    Token rule: the token after ``prev`` (sitting at ``pos``) is
    ``(prev * 7 + pos * 13 + 1) % vocab`` — a pure function of the carry,
    so any scheduling (per-token, fused burst, dispatch-ahead, preempt +
    re-prefill resume) must reproduce the same stream.
    """

    def __init__(self, config: EngineConfig):
        self.config = config
        self.v = config.model.vocab_size
        self.step_calls = 0
        self.burst_calls = 0
        self.chained_calls = 0

    def _advance(self, prev, pos):
        return (prev * 7 + pos * 13 + 1) % self.v

    # sampling-state writes are host bookkeeping the fake doesn't need
    def set_sample_row(self, *a, **kw):
        pass

    def set_bias_row(self, *a, **kw):
        pass

    def edit_bias_entries(self, *a, **kw):
        return True

    def step(self, tokens, positions, btab, slot_map, ctx_lens, last_idx,
             *args, **kw):
        self.step_calls += 1
        tokens = np.asarray(tokens)
        b = tokens.shape[0]
        rows = np.arange(b)
        last_idx = np.asarray(last_idx)
        prev = tokens[rows, last_idx]
        pos = np.asarray(positions)[rows, last_idx]
        nt = self._advance(prev, pos).astype(np.int32)
        lps = (-(nt % 7) / 10.0).astype(np.float32)
        tv = np.zeros((b, 8), np.float32)
        ti = np.zeros((b, 8), np.int32)
        plps = np.zeros(tokens.shape, np.float32)
        greedy = np.zeros(tokens.shape, np.int32)
        return nt, lps, tv, ti, plps, greedy

    def decode_burst(self, tokens0, positions0, btab, *args,
                     commit=None, want_top=False, **kw):
        self.burst_calls += 1
        K = max(1, self.config.multi_step_decode)
        prev = np.asarray(tokens0).astype(np.int64).copy()
        pos = np.asarray(positions0).astype(np.int64).copy()
        b = prev.shape[0]
        toks = np.zeros((K, b), np.int32)
        lps = np.zeros((K, b), np.float32)
        for s in range(K):
            prev = self._advance(prev, pos)
            toks[s] = prev
            lps[s] = -(toks[s] % 7) / 10.0
            pos += 1
        tv = np.zeros((K, b, 8), np.float32)
        ti = np.zeros((K, b, 8), np.int32)
        return toks, lps, tv, ti

    def decode_burst_chained(self, tokens0, positions0, gen0, done0, btab,
                             *args, commit=None, stop_ids=None,
                             min_new=None, max_new=None, want_top=False,
                             **kw):
        """Host mirror of the device-finish burst: same token rule, plus
        the freeze semantics — finished rows stop advancing and emit -1
        pads; the carry (tokens/pos/gen/done) feeds the next call."""
        self.chained_calls += 1
        K = max(1, self.config.multi_step_decode)
        prev = np.asarray(tokens0).astype(np.int64).copy()
        pos = np.asarray(positions0).astype(np.int64).copy()
        gen = np.asarray(gen0).astype(np.int64).copy()
        done = np.asarray(done0).astype(bool).copy()
        commit = np.asarray(commit).astype(bool)
        b = prev.shape[0]
        toks = np.full((K, b), -1, np.int32)
        lps = np.zeros((K, b), np.float32)
        max_len = self.config.max_model_len
        for s in range(K):
            live = commit & ~done
            nt = self._advance(prev, pos)
            gen = gen + live.astype(np.int64)
            hit = (nt[:, None] == np.asarray(stop_ids)).any(axis=1)
            newly = live & (
                ((gen >= min_new) & hit)
                | (gen >= max_new) | (pos + 2 >= max_len)
            )
            toks[s] = np.where(live, nt, -1)
            lps[s] = np.where(live, -(nt % 7) / 10.0, 0.0)
            adv = live & ~newly
            prev = np.where(adv, nt, prev)
            pos = np.where(adv, pos + 1, pos)
            done = done | newly
        tv = np.zeros((K, b, 8), np.float32)
        ti = np.zeros((K, b, 8), np.int32)
        return toks, lps, tv, ti, (
            prev.astype(np.int32), pos.astype(np.int32),
            gen.astype(np.int32), done,
        )


def _config(depth, k=4, **kw):
    kw.setdefault("num_kv_blocks", 64)
    kw.setdefault("max_model_len", 128)
    return EngineConfig(
        model=ModelConfig(vocab_size=512, hidden_size=32,
                          intermediate_size=64, num_layers=1, num_heads=2,
                          num_kv_heads=1),
        max_batch_size=4, kv_block_size=8,
        dtype="float32", multi_step_decode=k, decode_pipeline_depth=depth,
        enable_prefix_caching=False, **kw,
    )


def _request(prompt, max_tokens, eos=None, sampling=None):
    req = PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(
            max_tokens=max_tokens, ignore_eos=eos is None,
        ),
        sampling_options=sampling or SamplingOptions(temperature=0.0),
        eos_token_ids=list(eos or []),
    )
    return EngineRequest(
        request_id=uuid.uuid4().hex, prompt=list(prompt), req=req,
        ctx=AsyncEngineContext(), out_queue=asyncio.Queue(),
    )


def _run(config, requests, hooks=None):
    """Drive the scheduler over a FakeRunner; returns (streams, sched)."""

    async def go():
        runner = FakeRunner(config)
        sched = Scheduler(runner, config)
        if hooks:
            hooks(sched)
        sched.start()

        async def collect(er):
            toks, finish = [], None
            while True:
                out = await er.out_queue.get()
                if out is None:
                    return toks, finish
                toks.extend(out.token_ids)
                if out.finish_reason is not None:
                    finish = out.finish_reason
        try:
            for er in requests:
                sched.add_request(er)
            return await asyncio.gather(*(collect(er) for er in requests))
        finally:
            await sched.stop()

    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(go())
    finally:
        loop.close()


PROMPTS = ([1, 17, 43], [2, 5], [9, 9, 9, 9, 9])


def _streams(depth, max_tokens=21, eos=None, k=4, sched_out=None, **cfg_kw):
    config = _config(depth, k=k, **cfg_kw)
    reqs = [_request(p, max_tokens, eos=eos) for p in PROMPTS]
    captured = {}

    def grab(s):
        captured["sched"] = s

    out = _run(config, reqs, hooks=grab)
    if sched_out is not None:
        sched_out.update(captured)
    return out


def test_differential_greedy_streams_identical():
    """Pipelined greedy decode must emit byte-identical streams vs sync —
    token ids, logprob carriers, and finish reasons."""
    box = {}
    want = _streams(1)
    got = _streams(2, sched_out=box)
    assert got == want
    assert box["sched"].pipeline_bursts > 0, "pipeline never engaged"
    assert box["sched"]._inflight is None


def test_eos_one_burst_late_stream_identical():
    """EOS lands mid-burst and is detected one burst late under depth 2:
    the over-decoded rows must be truncated so the stream (and finish
    reason) is identical to the sync path, and every rolled-back block
    must return to the allocator."""
    # find the greedy continuation, then make its 6th token the eos: with
    # K=4 it lands in burst 2 while burst 3 is already in flight
    plain = _streams(1, max_tokens=24)
    eos = [plain[0][0][5]]
    want = _streams(1, max_tokens=24, eos=eos)
    assert want[0][1] == "eos" and len(want[0][0]) <= 6
    box = {}
    got = _streams(2, max_tokens=24, eos=eos, sched_out=box)
    assert got == want
    sched = box["sched"]
    assert sched.pipeline_bursts > 0
    assert sched.allocator.used == 0  # headroom + rollback leak nothing


def test_single_step_pipeline_identical():
    assert _streams(2, k=1) == _streams(1, k=1)


def test_preemption_drains_pipeline_and_stream_continues():
    """KV OOM under dispatch-ahead must force a sync barrier (drain)
    before preemption — and the resumed streams still total max_tokens
    with the identical prefix, matching the unconstrained run."""
    want = _streams(1, max_tokens=24, num_kv_blocks=64)

    preempts = []

    def hook(sched):
        orig = sched._preempt

        def spy(er):
            # the pipeline must be fully reconciled when preemption runs
            assert sched._inflight is None, \
                "preempted with a burst still in flight"
            preempts.append(er.request_id)
            orig(er)

        sched._preempt = spy

    # (3 prompts + 24 new tokens) doesn't fit in 10 blocks even at the
    # sync path's K-position reservation, so the pipelined OOM first
    # degrades to sync (drain) and the sync path then preempts
    config = _config(2, num_kv_blocks=10)
    reqs = [_request(p, 24) for p in PROMPTS]
    box = {}

    def hooks(s):
        box["sched"] = s
        hook(s)

    got = _run(config, reqs, hooks=hooks)
    assert preempts, "test is vacuous: no preemption happened"
    assert box["sched"].pipeline_bursts > 0, "pipeline never engaged"
    assert got == want


def _pipeline_stays_cold(config, reqs):
    box = {}

    def grab(s):
        box["sched"] = s

    out = _run(config, reqs, hooks=grab)
    sched = box["sched"]
    assert sched.pipeline_bursts == 0, "pipelined dispatch on a sync-only shape"
    assert sched._inflight is None
    return out


def test_guided_requests_force_sync_path():
    config = _config(2)
    sampling = SamplingOptions(
        temperature=0.0,
        guided_choice_token_ids=[[3, 4, 5], [3, 7]],
    )
    reqs = [_request([1, 2], 8, sampling=sampling)]
    out = _pipeline_stays_cold(config, reqs)
    assert out[0][1] is not None  # the request still completes


def test_spec_decode_forces_sync_path():
    config = _config(2, spec_ngram_tokens=2, spec_ngram_match=2)
    reqs = [_request([1, 2, 1, 2, 1, 2], 8)]
    _pipeline_stays_cold(config, reqs)


def test_n_gt_1_forces_sync_path():
    # serving rejects n>1 today; the scheduler still guards in case a
    # future fan-out path feeds multi-choice requests straight in
    config = _config(2)
    reqs = [_request([1, 2, 3], 8,
                     sampling=SamplingOptions(temperature=0.0, n=2))]
    _pipeline_stays_cold(config, reqs)


def test_prefill_arrival_drains_then_resumes_pipeline():
    """A new admission mid-decode forces the sync path (runner no longer
    idle) and the pipeline re-engages afterwards — outputs unchanged."""
    config = _config(2)

    async def go():
        runner = FakeRunner(config)
        sched = Scheduler(runner, config)
        sched.start()

        async def collect(er):
            toks = []
            while True:
                out = await er.out_queue.get()
                if out is None:
                    return toks
                toks.extend(out.token_ids)

        first = _request(PROMPTS[0], 30)
        sched.add_request(first)
        t1 = asyncio.ensure_future(collect(first))
        await asyncio.sleep(0.05)  # let the pipeline engage
        engaged = sched.pipeline_bursts
        late = _request(PROMPTS[1], 30)
        sched.add_request(late)
        t2 = asyncio.ensure_future(collect(late))
        out = [await t1, await t2]
        bursts = sched.pipeline_bursts
        await sched.stop()
        return engaged, bursts, out

    loop = asyncio.new_event_loop()
    try:
        engaged, bursts, got = loop.run_until_complete(go())
    finally:
        loop.close()
    assert engaged > 0, "pipeline never engaged before the late arrival"
    assert bursts > engaged, "pipeline never re-engaged after the drain"
    want = _streams(1, max_tokens=30)
    assert got[0] == want[0][0]
    assert got[1] == want[1][0]


def test_near_horizon_rows_fall_back_to_sync():
    """Rows within two bursts of max_model_len must decode synchronously
    (the burst would write past the block-table horizon) and still end
    with finish reason length at the same point as the sync path."""
    want = _streams(1, max_tokens=200, max_model_len=32)
    box = {}
    got = _streams(2, max_tokens=200, max_model_len=32,
                   device_finish="off", sched_out=box)
    assert got == want
    assert all(f == "length" for _, f in got)
    assert box["sched"]._inflight is None


# --------------------------------------------------------------------------
# device-resident finish detection (config.device_finish) — the
# persistent decode loop: chained bursts, frozen rows, async row drain
# --------------------------------------------------------------------------


def test_device_finish_differential_streams_identical():
    """Streams must be byte-identical with device-finish on vs off —
    token ids, logprob carriers, finish reasons — and the chained path
    must actually engage: bursts dispatched between host barriers > 1
    (the host barrier is no longer per burst)."""
    want = _streams(1)
    off_box, on_box = {}, {}
    off = _streams(2, device_finish="off", sched_out=off_box)
    on = _streams(2, sched_out=on_box)  # auto: enabled at depth 2
    assert off == want
    assert on == want
    assert off_box["sched"].runner.chained_calls == 0
    sched = on_box["sched"]
    assert sched.runner.chained_calls > 1
    assert sched._last_chain_len > 1, "host barrier still per burst"
    assert not sched._chain and not sched._chain_members
    # every finish was detected on device (all rows are device-checkable)
    assert sum(sched._device_finished_ctr.values.values()) == len(PROMPTS)


def test_device_finish_eos_mid_burst_freezes_row():
    """EOS landing mid-burst under device finish: the row freezes ON
    DEVICE at exactly the stop token (no over-decode at all — nothing
    emits after it), the stream matches the sync path byte-for-byte,
    and the reserved headroom blocks all roll back."""
    plain = _streams(1, max_tokens=24)
    eos = [plain[0][0][5]]  # lands mid-burst at K=4
    want = _streams(1, max_tokens=24, eos=eos)
    assert want[0][1] == "eos" and len(want[0][0]) <= 6
    box = {}
    got = _streams(2, max_tokens=24, eos=eos, sched_out=box)
    assert got == want
    sched = box["sched"]
    assert sched.runner.chained_calls > 0
    assert sum(sched._device_finished_ctr.values.values()) >= 1
    assert sched.allocator.used == 0  # headroom + rollback leak nothing


def test_device_finish_max_tokens_at_burst_boundary():
    """max_tokens an exact multiple of K: the LENGTH finish lands on the
    last step of a burst — the device mask must freeze the row there
    (not one burst late) and the stream must match the sync path."""
    for mt in (8, 12):  # K=4 boundaries
        want = _streams(1, max_tokens=mt)
        box = {}
        got = _streams(2, max_tokens=mt, sched_out=box)
        assert got == want
        assert all(len(toks) == mt and f == "length" for toks, f in got)
        assert box["sched"].runner.chained_calls > 0
        assert box["sched"].allocator.used == 0


def test_stop_string_rows_forced_to_sync_path():
    """Stop STRINGS need the backend's host-side post-check (the jail) —
    such rows are classified not-device-checkable at admission and the
    chain must never engage; the PR 3 per-burst-reconciled pipeline
    serves them instead, with the stream unchanged."""
    config = _config(2)

    def reqs():
        out = []
        for p in PROMPTS:
            req = PreprocessedRequest(
                token_ids=list(p),
                stop_conditions=StopConditions(max_tokens=12, ignore_eos=True,
                                               stop=["never-matches"]),
                sampling_options=SamplingOptions(temperature=0.0),
                eos_token_ids=[],
            )
            out.append(EngineRequest(
                request_id=uuid.uuid4().hex, prompt=list(p), req=req,
                ctx=AsyncEngineContext(), out_queue=asyncio.Queue(),
            ))
        return out
    rs = reqs()
    assert all(not er.device_checkable for er in rs)
    box = {}

    def grab(s):
        box["sched"] = s

    got = _run(config, rs, hooks=grab)
    sched = box["sched"]
    assert sched.runner.chained_calls == 0, "chained a stop-string row"
    assert sched.pipeline_bursts > 0, "PR 3 pipeline should still engage"
    want = _run(_config(1), reqs())
    assert got == want


def test_preemption_kv_oom_drains_chain_before_membership_changes():
    """KV OOM mid-chain must run the chain barrier (every queued burst
    reconciled, membership compacted) before preemption touches any
    row — and the resumed streams still match the unconstrained run."""
    want = _streams(1, max_tokens=24, num_kv_blocks=64)

    preempts = []

    def hook(sched):
        orig = sched._preempt

        def spy(er):
            assert not sched._chain, "preempted with chained bursts in flight"
            assert not sched._chain_members, \
                "preempted before the chain membership barrier"
            assert sched._inflight is None
            preempts.append(er.request_id)
            orig(er)

        sched._preempt = spy

    config = _config(2, num_kv_blocks=10)
    reqs = [_request(p, 24) for p in PROMPTS]
    box = {}

    def hooks(s):
        box["sched"] = s
        hook(s)

    got = _run(config, reqs, hooks=hooks)
    assert preempts, "test is vacuous: no preemption happened"
    assert box["sched"].runner.chained_calls > 0, "chain never engaged"
    assert got == want


def test_device_finish_near_horizon_rows_stay_chained():
    """Under device finish, rows near max_model_len do NOT fall back to
    sync (the PR 3 behavior): the device's LENGTH check (pos + 2 >=
    max_model_len — the in-scan mirror of _check_finish's context_len +
    1 bound) freezes them at exactly the horizon, headroom reservation
    caps at max_model_len - 1, and the streams still match the sync
    path byte-for-byte."""
    want = _streams(1, max_tokens=200, max_model_len=32)
    box = {}
    got = _streams(2, max_tokens=200, max_model_len=32, sched_out=box)
    assert got == want
    assert all(f == "length" for _, f in got)
    sched = box["sched"]
    assert sched.runner.chained_calls > 0, \
        "near-horizon rows forced sync under device finish"
    # every LENGTH finish at the horizon was detected on device
    assert sum(sched._device_finished_ctr.values.values()) == len(PROMPTS)
    assert sched.allocator.used == 0
    assert not sched._chain and not sched._chain_members


def test_late_drain_retro_invalidation_rolls_back_blocks():
    """The chain reserves block headroom against its own dispatch count,
    so a row finishing deep into a chain holds blocks covering positions
    it froze before reaching — the drain's retro-invalidation must roll
    that tail back into the allocator (rollback_tail observed with a
    shrinking keep) and leak nothing."""
    rollbacks = []

    def hook(sched):
        orig = sched.allocator.rollback_tail

        def spy(block_ids, keep):
            rollbacks.append((len(block_ids), keep))
            return orig(block_ids, keep)

        sched.allocator.rollback_tail = spy

    plain = _streams(1, max_tokens=24)
    eos = [plain[2][0][2]]  # row 2 stops early, deep headroom reserved
    config = _config(2, num_kv_blocks=64)
    reqs = [_request(p, 24, eos=eos) for p in PROMPTS]
    box = {}

    def hooks(s):
        box["sched"] = s
        hook(s)

    _run(config, reqs, hooks=hooks)
    sched = box["sched"]
    assert sched.runner.chained_calls > 0
    assert any(total > keep for total, keep in rollbacks), \
        "no over-reserved tail was ever rolled back"
    assert sched.allocator.used == 0


# --------------------------------------------------------------------------
# device-time attribution on the chained path (telemetry/device_time.py)
# --------------------------------------------------------------------------


def _run_chained(with_tracker):
    """Drive the persistent loop over a FakeRunner, spying on the host
    syncs (_observe_host_sync — every executor-side device sync passes
    through it). Returns (streams, sync_count, tracker_or_None)."""
    from dynamo_tpu.telemetry.device_time import DeviceTimeTracker

    config = _config(2)  # device_finish auto → on at depth 2
    reqs = [_request(p, 21) for p in PROMPTS]
    syncs = []
    box = {}

    async def go():
        runner = FakeRunner(config)
        tracker = None
        if with_tracker:
            tracker = DeviceTimeTracker(
                param_bytes=1e9, kv_bytes_per_token=1e3, hbm_gbps=100.0,
            )
            runner.device_time = tracker
        sched = Scheduler(runner, config)
        box["sched"] = sched
        orig = sched._observe_host_sync

        def spy(dt):
            syncs.append(dt)
            orig(dt)

        sched._observe_host_sync = spy
        sched.start()

        async def collect(er):
            toks, finish = [], None
            while True:
                out = await er.out_queue.get()
                if out is None:
                    return toks, finish
                toks.extend(out.token_ids)
                if out.finish_reason is not None:
                    finish = out.finish_reason
        try:
            for er in reqs:
                sched.add_request(er)
            return await asyncio.gather(*(collect(er) for er in reqs)), tracker
        finally:
            await sched.stop()

    loop = asyncio.new_event_loop()
    try:
        streams, tracker = loop.run_until_complete(go())
    finally:
        loop.close()
    return streams, len(syncs), tracker


def test_device_time_chained_adds_no_host_syncs_and_attributes_bursts():
    """The device-time tracker measures off the async drain's EXISTING
    reconciliation seams: with it attached, the chained path performs
    exactly the same number of host syncs, the streams are byte-
    identical, and every chained burst lands as a decode_burst_df
    observation with nonzero busy time + a live roofline fraction."""
    base_streams, base_syncs, _ = _run_chained(with_tracker=False)
    streams, syncs, tracker = _run_chained(with_tracker=True)
    assert streams == base_streams
    assert syncs == base_syncs, "device-time tracking added a host sync"
    assert tracker is not None and tracker.observations > 0
    assert tracker.busy_s.get("decode", 0.0) > 0.0
    assert box_chained_calls(tracker) > 0
    text = tracker.registry.render()
    assert "dynamo_engine_device_time_seconds" in text
    assert "dynamo_engine_roofline_fraction" in text
    ((_, frac),) = tracker._roofline()
    assert frac > 0.0
    # the chained program is what got attributed (alongside the prefill)
    programs = {dict(k).get("program") for k in tracker._time_hist.counts}
    assert "decode_burst_df" in programs
    phases = {dict(k).get("phase") for k in tracker._time_hist.counts}
    assert phases <= {"decode", "prefill"}


def box_chained_calls(tracker):
    # decode tokens accumulated via the burst token accounting
    return tracker.decode_tokens
