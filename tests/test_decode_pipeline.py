"""Dispatch-ahead decode pipeline (EngineConfig.decode_pipeline_depth=2)
edge cases, driven by a deterministic fake runner.

The fake models exactly the carry semantics the pipeline relies on —
``next = f(prev_token, position)`` — so the synchronous and pipelined
schedulers must produce byte-identical streams through every edge:
finishes detected one burst late, preemption forcing a drain, and the
guided/spec/``n>1`` fallbacks. The real-model differential lives in
tests/test_multi_step.py; this file isolates the SCHEDULER's pipeline
logic from the numerics.
"""

import asyncio
import uuid

import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.scheduler import EngineRequest, Scheduler
from dynamo_tpu.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.engine import AsyncEngineContext


class FakeRunner:
    """Deterministic stand-in for ModelRunner.

    Token rule: the token after ``prev`` (sitting at ``pos``) is
    ``(prev * 7 + pos * 13 + 1) % vocab`` — a pure function of the carry,
    so any scheduling (per-token, fused burst, dispatch-ahead, preempt +
    re-prefill resume) must reproduce the same stream.
    """

    def __init__(self, config: EngineConfig):
        self.config = config
        self.v = config.model.vocab_size
        self.step_calls = 0
        self.burst_calls = 0

    def _advance(self, prev, pos):
        return (prev * 7 + pos * 13 + 1) % self.v

    # sampling-state writes are host bookkeeping the fake doesn't need
    def set_sample_row(self, *a, **kw):
        pass

    def set_bias_row(self, *a, **kw):
        pass

    def edit_bias_entries(self, *a, **kw):
        return True

    def step(self, tokens, positions, btab, slot_map, ctx_lens, last_idx,
             *args, **kw):
        self.step_calls += 1
        tokens = np.asarray(tokens)
        b = tokens.shape[0]
        rows = np.arange(b)
        last_idx = np.asarray(last_idx)
        prev = tokens[rows, last_idx]
        pos = np.asarray(positions)[rows, last_idx]
        nt = self._advance(prev, pos).astype(np.int32)
        lps = (-(nt % 7) / 10.0).astype(np.float32)
        tv = np.zeros((b, 8), np.float32)
        ti = np.zeros((b, 8), np.int32)
        plps = np.zeros(tokens.shape, np.float32)
        greedy = np.zeros(tokens.shape, np.int32)
        return nt, lps, tv, ti, plps, greedy

    def decode_burst(self, tokens0, positions0, btab, *args,
                     commit=None, want_top=False, **kw):
        self.burst_calls += 1
        K = max(1, self.config.multi_step_decode)
        prev = np.asarray(tokens0).astype(np.int64).copy()
        pos = np.asarray(positions0).astype(np.int64).copy()
        b = prev.shape[0]
        toks = np.zeros((K, b), np.int32)
        lps = np.zeros((K, b), np.float32)
        for s in range(K):
            prev = self._advance(prev, pos)
            toks[s] = prev
            lps[s] = -(toks[s] % 7) / 10.0
            pos += 1
        tv = np.zeros((K, b, 8), np.float32)
        ti = np.zeros((K, b, 8), np.int32)
        return toks, lps, tv, ti


def _config(depth, k=4, **kw):
    kw.setdefault("num_kv_blocks", 64)
    kw.setdefault("max_model_len", 128)
    return EngineConfig(
        model=ModelConfig(vocab_size=512, hidden_size=32,
                          intermediate_size=64, num_layers=1, num_heads=2,
                          num_kv_heads=1),
        max_batch_size=4, kv_block_size=8,
        dtype="float32", multi_step_decode=k, decode_pipeline_depth=depth,
        enable_prefix_caching=False, **kw,
    )


def _request(prompt, max_tokens, eos=None, sampling=None):
    req = PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(
            max_tokens=max_tokens, ignore_eos=eos is None,
        ),
        sampling_options=sampling or SamplingOptions(temperature=0.0),
        eos_token_ids=list(eos or []),
    )
    return EngineRequest(
        request_id=uuid.uuid4().hex, prompt=list(prompt), req=req,
        ctx=AsyncEngineContext(), out_queue=asyncio.Queue(),
    )


def _run(config, requests, hooks=None):
    """Drive the scheduler over a FakeRunner; returns (streams, sched)."""

    async def go():
        runner = FakeRunner(config)
        sched = Scheduler(runner, config)
        if hooks:
            hooks(sched)
        sched.start()

        async def collect(er):
            toks, finish = [], None
            while True:
                out = await er.out_queue.get()
                if out is None:
                    return toks, finish
                toks.extend(out.token_ids)
                if out.finish_reason is not None:
                    finish = out.finish_reason
        try:
            for er in requests:
                sched.add_request(er)
            return await asyncio.gather(*(collect(er) for er in requests))
        finally:
            await sched.stop()

    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(go())
    finally:
        loop.close()


PROMPTS = ([1, 17, 43], [2, 5], [9, 9, 9, 9, 9])


def _streams(depth, max_tokens=21, eos=None, k=4, sched_out=None, **cfg_kw):
    config = _config(depth, k=k, **cfg_kw)
    reqs = [_request(p, max_tokens, eos=eos) for p in PROMPTS]
    captured = {}

    def grab(s):
        captured["sched"] = s

    out = _run(config, reqs, hooks=grab)
    if sched_out is not None:
        sched_out.update(captured)
    return out


def test_differential_greedy_streams_identical():
    """Pipelined greedy decode must emit byte-identical streams vs sync —
    token ids, logprob carriers, and finish reasons."""
    box = {}
    want = _streams(1)
    got = _streams(2, sched_out=box)
    assert got == want
    assert box["sched"].pipeline_bursts > 0, "pipeline never engaged"
    assert box["sched"]._inflight is None


def test_eos_one_burst_late_stream_identical():
    """EOS lands mid-burst and is detected one burst late under depth 2:
    the over-decoded rows must be truncated so the stream (and finish
    reason) is identical to the sync path, and every rolled-back block
    must return to the allocator."""
    # find the greedy continuation, then make its 6th token the eos: with
    # K=4 it lands in burst 2 while burst 3 is already in flight
    plain = _streams(1, max_tokens=24)
    eos = [plain[0][0][5]]
    want = _streams(1, max_tokens=24, eos=eos)
    assert want[0][1] == "eos" and len(want[0][0]) <= 6
    box = {}
    got = _streams(2, max_tokens=24, eos=eos, sched_out=box)
    assert got == want
    sched = box["sched"]
    assert sched.pipeline_bursts > 0
    assert sched.allocator.used == 0  # headroom + rollback leak nothing


def test_single_step_pipeline_identical():
    assert _streams(2, k=1) == _streams(1, k=1)


def test_preemption_drains_pipeline_and_stream_continues():
    """KV OOM under dispatch-ahead must force a sync barrier (drain)
    before preemption — and the resumed streams still total max_tokens
    with the identical prefix, matching the unconstrained run."""
    want = _streams(1, max_tokens=24, num_kv_blocks=64)

    preempts = []

    def hook(sched):
        orig = sched._preempt

        def spy(er):
            # the pipeline must be fully reconciled when preemption runs
            assert sched._inflight is None, \
                "preempted with a burst still in flight"
            preempts.append(er.request_id)
            orig(er)

        sched._preempt = spy

    # (3 prompts + 24 new tokens) doesn't fit in 10 blocks even at the
    # sync path's K-position reservation, so the pipelined OOM first
    # degrades to sync (drain) and the sync path then preempts
    config = _config(2, num_kv_blocks=10)
    reqs = [_request(p, 24) for p in PROMPTS]
    box = {}

    def hooks(s):
        box["sched"] = s
        hook(s)

    got = _run(config, reqs, hooks=hooks)
    assert preempts, "test is vacuous: no preemption happened"
    assert box["sched"].pipeline_bursts > 0, "pipeline never engaged"
    assert got == want


def _pipeline_stays_cold(config, reqs):
    box = {}

    def grab(s):
        box["sched"] = s

    out = _run(config, reqs, hooks=grab)
    sched = box["sched"]
    assert sched.pipeline_bursts == 0, "pipelined dispatch on a sync-only shape"
    assert sched._inflight is None
    return out


def test_guided_requests_force_sync_path():
    config = _config(2)
    sampling = SamplingOptions(
        temperature=0.0,
        guided_choice_token_ids=[[3, 4, 5], [3, 7]],
    )
    reqs = [_request([1, 2], 8, sampling=sampling)]
    out = _pipeline_stays_cold(config, reqs)
    assert out[0][1] is not None  # the request still completes


def test_spec_decode_forces_sync_path():
    config = _config(2, spec_ngram_tokens=2, spec_ngram_match=2)
    reqs = [_request([1, 2, 1, 2, 1, 2], 8)]
    _pipeline_stays_cold(config, reqs)


def test_n_gt_1_forces_sync_path():
    # serving rejects n>1 today; the scheduler still guards in case a
    # future fan-out path feeds multi-choice requests straight in
    config = _config(2)
    reqs = [_request([1, 2, 3], 8,
                     sampling=SamplingOptions(temperature=0.0, n=2))]
    _pipeline_stays_cold(config, reqs)


def test_prefill_arrival_drains_then_resumes_pipeline():
    """A new admission mid-decode forces the sync path (runner no longer
    idle) and the pipeline re-engages afterwards — outputs unchanged."""
    config = _config(2)

    async def go():
        runner = FakeRunner(config)
        sched = Scheduler(runner, config)
        sched.start()

        async def collect(er):
            toks = []
            while True:
                out = await er.out_queue.get()
                if out is None:
                    return toks
                toks.extend(out.token_ids)

        first = _request(PROMPTS[0], 30)
        sched.add_request(first)
        t1 = asyncio.ensure_future(collect(first))
        await asyncio.sleep(0.05)  # let the pipeline engage
        engaged = sched.pipeline_bursts
        late = _request(PROMPTS[1], 30)
        sched.add_request(late)
        t2 = asyncio.ensure_future(collect(late))
        out = [await t1, await t2]
        bursts = sched.pipeline_bursts
        await sched.stop()
        return engaged, bursts, out

    loop = asyncio.new_event_loop()
    try:
        engaged, bursts, got = loop.run_until_complete(go())
    finally:
        loop.close()
    assert engaged > 0, "pipeline never engaged before the late arrival"
    assert bursts > engaged, "pipeline never re-engaged after the drain"
    want = _streams(1, max_tokens=30)
    assert got[0] == want[0][0]
    assert got[1] == want[1][0]


def test_near_horizon_rows_fall_back_to_sync():
    """Rows within two bursts of max_model_len must decode synchronously
    (the burst would write past the block-table horizon) and still end
    with finish reason length at the same point as the sync path."""
    want = _streams(1, max_tokens=200, max_model_len=32)
    box = {}
    got = _streams(2, max_tokens=200, max_model_len=32, sched_out=box)
    assert got == want
    assert all(f == "length" for _, f in got)
    assert box["sched"]._inflight is None
