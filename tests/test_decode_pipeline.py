"""Dispatch-ahead decode pipeline (EngineConfig.decode_pipeline_depth=2)
edge cases, driven by a deterministic fake runner.

The fake models exactly the carry semantics the pipeline relies on —
``next = f(prev_token, position)`` — so the synchronous and pipelined
schedulers must produce byte-identical streams through every edge:
finishes detected one burst late, preemption forcing a drain, and the
guided/spec/``n>1`` fallbacks. The real-model differential lives in
tests/test_multi_step.py; this file isolates the SCHEDULER's pipeline
logic from the numerics.
"""

import asyncio
import uuid

import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.scheduler import EngineRequest, Scheduler
from dynamo_tpu.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.engine import AsyncEngineContext


class FakeRunner:
    """Deterministic stand-in for ModelRunner.

    Token rule: the token after ``prev`` (sitting at ``pos``) is the
    bias-row argmax of ``-(|id - target|)`` with ``target = (prev * 7 +
    pos * 13 + 1) % vocab`` — a pure function of the carry and the
    slot's installed mask, so any scheduling (per-token, fused burst,
    dispatch-ahead, chained, guided via bias rows OR via the device
    transition table, preempt + re-prefill resume) must reproduce the
    same stream. With a zero bias row the argmax IS ``target`` (the
    original rule); a guided mask steers it to the nearest allowed id
    identically on the host-mask path and the device-table path.
    """

    spec_burst_ready = True

    def __init__(self, config: EngineConfig):
        self.config = config
        self.v = config.model.vocab_size
        self.step_calls = 0
        self.burst_calls = 0
        self.chained_calls = 0
        self.spec_calls = 0
        self.bias = np.zeros((config.max_batch_size, self.v), np.float32)
        # test hook: force a stop-string suffix-hash candidate (the
        # device false-positive injection) — fn(slot, gen) -> bool
        self.force_stop_candidate = None

    def _advance(self, prev, pos):
        return (prev * 7 + pos * 13 + 1) % self.v

    def _tok(self, prev, pos, slot=None, extra_mask=None):
        """One sampled token: bias-aware argmax (mirrors sample())."""
        target = int((int(prev) * 7 + int(pos) * 13 + 1) % self.v)
        row = self.bias[slot] if slot is not None else None
        if (row is None or not row.any()) and extra_mask is None:
            return target
        logits = -np.abs(
            np.arange(self.v) - target
        ).astype(np.float64)
        if row is not None:
            logits = logits + row
        if extra_mask is not None:
            logits = logits + extra_mask
        return int(np.argmax(logits))

    # sampling-state writes mirror only the bias row (guided masks +
    # logit_bias); counts/seen are penalty bookkeeping the fake's
    # deterministic rule never consults
    def set_sample_row(self, slot, prompt_ids, generated_ids=(),
                       logit_bias=None, guided_mask=None):
        row = (
            np.asarray(guided_mask, np.float32).copy()
            if guided_mask is not None
            else np.zeros(self.v, np.float32)
        )
        for tid, b in (logit_bias or {}).items():
            tid = int(tid)
            if 0 <= tid < self.v:
                row[tid] += float(b)
        self.bias[slot] = row

    GUIDED_STATE_BUCKETS = (1, 64, 256, 1024)

    def guided_state_bucket(self, n_states):
        for s in self.GUIDED_STATE_BUCKETS:
            if n_states <= s:
                return s
        return self.GUIDED_STATE_BUCKETS[-1]

    def set_bias_row(self, slot, row):
        self.bias[slot] = np.asarray(row, np.float32).copy()

    def edit_bias_entries(self, slot, ids, vals):
        for t, val in zip(ids, vals):
            self.bias[slot][int(t)] = float(val)
        return True

    def step(self, tokens, positions, btab, slot_map, ctx_lens, last_idx,
             *args, sample_slots=None, want_greedy=False, **kw):
        self.step_calls += 1
        tokens = np.asarray(tokens)
        b = tokens.shape[0]
        rows = np.arange(b)
        last_idx = np.asarray(last_idx)
        prev = tokens[rows, last_idx]
        pos = np.asarray(positions)[rows, last_idx]
        slots = (np.asarray(sample_slots) if sample_slots is not None
                 else rows)
        nt = np.asarray([
            self._tok(prev[i], pos[i], slot=int(slots[i]))
            for i in range(b)
        ], np.int32)
        lps = (-(nt % 7) / 10.0).astype(np.float32)
        tv = np.zeros((b, 8), np.float32)
        ti = np.zeros((b, 8), np.int32)
        plps = np.zeros(tokens.shape, np.float32)
        # spec verify: per-position raw argmax (no bias — the real
        # verify reads raw logits), position-wise f(token_j, pos_j)
        if want_greedy:
            greedy = self._advance(
                tokens.astype(np.int64), np.asarray(positions)
            ).astype(np.int32)
        else:
            greedy = np.zeros(tokens.shape, np.int32)
        return nt, lps, tv, ti, plps, greedy

    def decode_burst(self, tokens0, positions0, btab, *args,
                     commit=None, want_top=False, **kw):
        self.burst_calls += 1
        K = max(1, self.config.multi_step_decode)
        prev = np.asarray(tokens0).astype(np.int64).copy()
        pos = np.asarray(positions0).astype(np.int64).copy()
        b = prev.shape[0]
        toks = np.zeros((K, b), np.int32)
        lps = np.zeros((K, b), np.float32)
        for s in range(K):
            prev = np.asarray([
                self._tok(prev[i], pos[i], slot=i) for i in range(b)
            ], np.int64)
            toks[s] = prev
            lps[s] = -(toks[s] % 7) / 10.0
            pos += 1
        tv = np.zeros((K, b, 8), np.float32)
        ti = np.zeros((K, b, 8), np.int32)
        return toks, lps, tv, ti

    # -- chained-path mirrors -------------------------------------------

    def _stop_candidate(self, ring_row, gen, min_new, hashes, lens, slot):
        from dynamo_tpu.engine.sampling import stop_seq_hash

        if self.force_stop_candidate is not None and \
                self.force_stop_candidate(slot, int(gen)):
            return True
        for h, ell in zip(hashes, lens):
            ell = int(ell)
            if ell > 0 and gen >= ell and gen >= min_new:
                if stop_seq_hash(ring_row[-ell:]) == int(h):
                    return True
        return False

    def decode_burst_chained(self, tokens0, positions0, gen0, done0, btab,
                             *args, commit=None, stop_ids=None,
                             min_new=None, max_new=None, ring0=None,
                             gstate0=None, stop_hash=None, stop_hlen=None,
                             gtable=None, want_top=False, **kw):
        """Host mirror of the device-finish burst: same token rule, plus
        the freeze semantics — finished rows stop advancing and emit -1
        pads; the carry (tokens/pos/gen/done/ring/gstate) feeds the next
        call. Guided rows mask through the transition table, stop-string
        rows through the rolling suffix hash, exactly like the device
        program."""
        self.chained_calls += 1
        K = max(1, self.config.multi_step_decode)
        prev = np.asarray(tokens0).astype(np.int64).copy()
        pos = np.asarray(positions0).astype(np.int64).copy()
        gen = np.asarray(gen0).astype(np.int64).copy()
        done = np.asarray(done0).astype(bool).copy()
        commit = np.asarray(commit).astype(bool)
        b = prev.shape[0]
        from dynamo_tpu.engine.sampling import SUFFIX_RING_W

        ring = (np.asarray(ring0, np.int64).copy() if ring0 is not None
                else np.full((b, SUFFIX_RING_W), -1, np.int64))
        gstate = (np.asarray(gstate0, np.int64).copy()
                  if gstate0 is not None else np.full(b, -1, np.int64))
        gtab = np.asarray(gtable) if gtable is not None else None
        hashes = (np.asarray(stop_hash) if stop_hash is not None
                  else np.zeros((b, 4), np.uint32))
        hlens = (np.asarray(stop_hlen) if stop_hlen is not None
                 else np.zeros((b, 4), np.int32))
        toks = np.full((K, b), -1, np.int32)
        lps = np.zeros((K, b), np.float32)
        max_len = self.config.max_model_len
        for s in range(K):
            live = commit & ~done
            nt = np.zeros(b, np.int64)
            for i in range(b):
                extra = None
                if gstate[i] >= 0 and gtab is not None:
                    extra = np.where(gtab[int(gstate[i])] < 0, -1e9, 0.0)
                nt[i] = self._tok(prev[i], pos[i], slot=i,
                                  extra_mask=extra)
            gen = gen + live.astype(np.int64)
            ring_n = np.concatenate([ring[:, 1:], nt[:, None]], axis=1)
            ring = np.where(live[:, None], ring_n, ring)
            hit = (nt[:, None] == np.asarray(stop_ids)).any(axis=1)
            hard = (
                ((gen >= min_new) & hit)
                | (gen >= max_new) | (pos + 2 >= max_len)
            )
            cand = np.asarray([
                live[i] and self._stop_candidate(
                    ring[i], gen[i], int(np.asarray(min_new)[i]),
                    hashes[i], hlens[i], i)
                for i in range(b)
            ], bool)
            gdone = np.zeros(b, bool)
            gnext = np.full(b, -1, np.int64)
            for i in range(b):
                if gstate[i] >= 0 and gtab is not None:
                    gnext[i] = int(gtab[int(gstate[i]), int(nt[i])])
                    gdone[i] = (not hard[i]) and gnext[i] <= 0
            newly = live & (hard | cand | gdone)
            toks[s] = np.where(live, nt, -1)
            lps[s] = np.where(live, -(nt % 7) / 10.0, 0.0)
            adv = live & ~newly
            prev = np.where(adv, nt, prev)
            pos = np.where(adv, pos + 1, pos)
            gstate = np.where(adv & (gstate >= 0), gnext, gstate)
            done = done | newly
        tv = np.zeros((K, b, 8), np.float32)
        ti = np.zeros((K, b, 8), np.int32)
        return toks, lps, tv, ti, (
            prev.astype(np.int32), pos.astype(np.int32),
            gen.astype(np.int32), done, ring.astype(np.int32),
            gstate.astype(np.int32),
        )

    def _ngram_from_ring(self, ring, m, k):
        w = len(ring)
        tail = ring[-m:]
        best = -1
        for s in range(w - m):
            win = ring[s:s + m]
            if (win == tail).all() and (win >= 0).all() \
                    and s + m + k <= w:
                best = s
        if best < 0:
            return [-1] * k
        return [int(t) if t >= 0 else -1
                for t in ring[best + m:best + m + k]]

    def decode_burst_spec(self, tokens0, positions0, gen0, done0, ring0,
                          gstate0, btab, *, commit, stop_ids, min_new,
                          max_new, stop_hash, stop_hlen, proposals=None):
        """Host mirror of the chained propose-verify round: ngram
        proposals from the ring, one-forward greedy verify, accepted
        prefix + correction committed with freeze semantics."""
        self.spec_calls += 1
        P = self.config.spec_ngram_tokens
        S = P + 1
        prev = np.asarray(tokens0).astype(np.int64).copy()
        pos = np.asarray(positions0).astype(np.int64).copy()
        gen = np.asarray(gen0).astype(np.int64).copy()
        done = np.asarray(done0).astype(bool).copy()
        ring = np.asarray(ring0, np.int64).copy()
        commit = np.asarray(commit).astype(bool)
        hashes = np.asarray(stop_hash)
        hlens = np.asarray(stop_hlen)
        b = prev.shape[0]
        max_len = self.config.max_model_len
        toks = np.full((S, b), -1, np.int32)
        nprop = np.zeros(b, np.int32)
        nacc = np.zeros(b, np.int32)
        for i in range(b):
            if not commit[i] or done[i]:
                continue
            props = (
                [int(t) for t in np.asarray(proposals)[i]]
                if proposals is not None
                else self._ngram_from_ring(
                    ring[i], self.config.spec_ngram_match, P)
            )
            nprop[i] = sum(1 for t in props if t >= 0)
            row = [int(prev[i])] + [t if t >= 0 else 0 for t in props]
            greedy = [
                int(self._advance(np.int64(row[j]), pos[i] + j))
                for j in range(S)
            ]
            acc = 0
            while acc < P and props[acc] >= 0 \
                    and greedy[acc] == props[acc]:
                acc += 1
            nacc[i] = acc  # raw verified proposals (sync-path semantics)
            for j in range(S):
                if done[i] or j > acc:
                    break
                t = greedy[j]
                gen[i] += 1
                ring[i] = np.concatenate([ring[i][1:], [t]])
                hit = t in set(int(x) for x in np.asarray(stop_ids)[i])
                hard = (
                    (gen[i] >= np.asarray(min_new)[i] and hit)
                    or gen[i] >= np.asarray(max_new)[i]
                    or pos[i] + 2 >= max_len
                )
                cand = self._stop_candidate(
                    ring[i], gen[i], int(np.asarray(min_new)[i]),
                    hashes[i], hlens[i], i)
                toks[j, i] = t
                if hard or cand:
                    done[i] = True
                else:
                    prev[i] = t
                    pos[i] += 1
        return toks, nprop, nacc, (
            prev.astype(np.int32), pos.astype(np.int32),
            gen.astype(np.int32), done, ring.astype(np.int32),
            np.asarray(gstate0, np.int32).copy(),
        )


def _config(depth, k=4, **kw):
    kw.setdefault("num_kv_blocks", 64)
    kw.setdefault("max_model_len", 128)
    return EngineConfig(
        model=ModelConfig(vocab_size=512, hidden_size=32,
                          intermediate_size=64, num_layers=1, num_heads=2,
                          num_kv_heads=1),
        max_batch_size=4, kv_block_size=8,
        dtype="float32", multi_step_decode=k, decode_pipeline_depth=depth,
        enable_prefix_caching=False, **kw,
    )


def _request(prompt, max_tokens, eos=None, sampling=None):
    req = PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(
            max_tokens=max_tokens, ignore_eos=eos is None,
        ),
        sampling_options=sampling or SamplingOptions(temperature=0.0),
        eos_token_ids=list(eos or []),
    )
    return EngineRequest(
        request_id=uuid.uuid4().hex, prompt=list(prompt), req=req,
        ctx=AsyncEngineContext(), out_queue=asyncio.Queue(),
    )


def _run(config, requests, hooks=None):
    """Drive the scheduler over a FakeRunner; returns (streams, sched)."""

    async def go():
        runner = FakeRunner(config)
        sched = Scheduler(runner, config)
        if hooks:
            hooks(sched)
        sched.start()

        async def collect(er):
            toks, finish = [], None
            while True:
                out = await er.out_queue.get()
                if out is None:
                    return toks, finish
                toks.extend(out.token_ids)
                if out.finish_reason is not None:
                    finish = out.finish_reason
        try:
            for er in requests:
                sched.add_request(er)
            return await asyncio.gather(*(collect(er) for er in requests))
        finally:
            await sched.stop()

    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(go())
    finally:
        loop.close()


PROMPTS = ([1, 17, 43], [2, 5], [9, 9, 9, 9, 9])


def _streams(depth, max_tokens=21, eos=None, k=4, sched_out=None, **cfg_kw):
    config = _config(depth, k=k, **cfg_kw)
    reqs = [_request(p, max_tokens, eos=eos) for p in PROMPTS]
    captured = {}

    def grab(s):
        captured["sched"] = s

    out = _run(config, reqs, hooks=grab)
    if sched_out is not None:
        sched_out.update(captured)
    return out


def test_differential_greedy_streams_identical():
    """Pipelined greedy decode must emit byte-identical streams vs sync —
    token ids, logprob carriers, and finish reasons."""
    box = {}
    want = _streams(1)
    got = _streams(2, sched_out=box)
    assert got == want
    assert box["sched"].pipeline_bursts > 0, "pipeline never engaged"
    assert box["sched"]._inflight is None


def test_eos_one_burst_late_stream_identical():
    """EOS lands mid-burst and is detected one burst late under depth 2:
    the over-decoded rows must be truncated so the stream (and finish
    reason) is identical to the sync path, and every rolled-back block
    must return to the allocator."""
    # find the greedy continuation, then make its 6th token the eos: with
    # K=4 it lands in burst 2 while burst 3 is already in flight
    plain = _streams(1, max_tokens=24)
    eos = [plain[0][0][5]]
    want = _streams(1, max_tokens=24, eos=eos)
    assert want[0][1] == "eos" and len(want[0][0]) <= 6
    box = {}
    got = _streams(2, max_tokens=24, eos=eos, sched_out=box)
    assert got == want
    sched = box["sched"]
    assert sched.pipeline_bursts > 0
    assert sched.allocator.used == 0  # headroom + rollback leak nothing


def test_single_step_pipeline_identical():
    assert _streams(2, k=1) == _streams(1, k=1)


def test_preemption_drains_pipeline_and_stream_continues():
    """KV OOM under dispatch-ahead must force a sync barrier (drain)
    before preemption — and the resumed streams still total max_tokens
    with the identical prefix, matching the unconstrained run."""
    want = _streams(1, max_tokens=24, num_kv_blocks=64)

    preempts = []

    def hook(sched):
        orig = sched._preempt

        def spy(er):
            # the pipeline must be fully reconciled when preemption runs
            assert sched._inflight is None, \
                "preempted with a burst still in flight"
            preempts.append(er.request_id)
            orig(er)

        sched._preempt = spy

    # (3 prompts + 24 new tokens) doesn't fit in 10 blocks even at the
    # sync path's K-position reservation, so the pipelined OOM first
    # degrades to sync (drain) and the sync path then preempts
    config = _config(2, num_kv_blocks=10)
    reqs = [_request(p, 24) for p in PROMPTS]
    box = {}

    def hooks(s):
        box["sched"] = s
        hook(s)

    got = _run(config, reqs, hooks=hooks)
    assert preempts, "test is vacuous: no preemption happened"
    assert box["sched"].pipeline_bursts > 0, "pipeline never engaged"
    assert got == want


def _pipeline_stays_cold(config, reqs):
    box = {}

    def grab(s):
        box["sched"] = s

    out = _run(config, reqs, hooks=grab)
    sched = box["sched"]
    assert sched.pipeline_bursts == 0, "pipelined dispatch on a sync-only shape"
    assert sched._inflight is None
    return out


def test_guided_requests_force_sync_path_when_table_disabled():
    """With guided_device_table off, guided rows keep the per-token host
    mask path (no chain, no pipeline burst) and the fallback counter
    names the reason."""
    config = _config(2, guided_device_table=False)
    sampling = SamplingOptions(
        temperature=0.0,
        guided_choice_token_ids=[[3, 4, 5], [3, 7]],
    )
    reqs = [_request([1, 2], 8, sampling=sampling)]
    out = _pipeline_stays_cold(config, reqs)
    assert out[0][1] is not None  # the request still completes


def _spec_config(depth, vocab=8, **kw):
    """Tiny-vocab spec config: an 8-token vocab makes the deterministic
    stream repetitive enough for ngram lookups to actually hit, so the
    acceptance path (not just the no-proposal round) is exercised."""
    kw.setdefault("num_kv_blocks", 64)
    kw.setdefault("max_model_len", 128)
    kw.setdefault("spec_ngram_tokens", 2)
    kw.setdefault("spec_ngram_match", 2)
    return EngineConfig(
        model=ModelConfig(vocab_size=vocab, hidden_size=32,
                          intermediate_size=64, num_layers=1, num_heads=2,
                          num_kv_heads=1),
        max_batch_size=4, kv_block_size=8,
        dtype="float32", multi_step_decode=4, decode_pipeline_depth=depth,
        enable_prefix_caching=False, **kw,
    )


def test_spec_decode_chains_and_streams_identical():
    """Ngram speculation now runs INSIDE the chain (propose-verify
    rounds off the device carry): streams must match the sync spec path
    byte-for-byte (which itself matches plain greedy decode — proposals
    affect acceptance, never content), the spec program must actually
    run, chain length must exceed 1 (no per-round host barrier), and
    the round's acceptance accounting must ride back."""
    reqs = lambda: [_request([1, 2, 1, 2, 1, 2], 24)]  # noqa: E731
    want = _run(_spec_config(1), reqs())
    plain = _run(_spec_config(1, spec_ngram_tokens=0), reqs())
    assert want == plain  # greedy spec never changes content
    box = {}

    def grab(s):
        box["sched"] = s

    got = _run(_spec_config(2), reqs(), hooks=grab)
    assert got == want
    sched = box["sched"]
    assert sched.runner.spec_calls > 1, "spec chain never engaged"
    assert sched._last_chain_len > 1, "host barrier still per round"
    assert sched.allocator.used == 0
    # acceptance accounting rode back from the device: proposals were
    # made and at least one round accepted speculative tokens
    assert sum(sched._spec_accept_hist.totals.values()) > 0
    assert sched.spec_proposed > 0
    assert sched.spec_accepted > 0


def test_n_gt_1_forces_sync_path():
    # serving rejects n>1 today; the scheduler still guards in case a
    # future fan-out path feeds multi-choice requests straight in
    config = _config(2)
    reqs = [_request([1, 2, 3], 8,
                     sampling=SamplingOptions(temperature=0.0, n=2))]
    _pipeline_stays_cold(config, reqs)


def test_prefill_arrival_drains_then_resumes_pipeline():
    """A new admission mid-decode forces the sync path (runner no longer
    idle) and the pipeline re-engages afterwards — outputs unchanged."""
    config = _config(2)

    async def go():
        runner = FakeRunner(config)
        sched = Scheduler(runner, config)
        sched.start()

        async def collect(er):
            toks = []
            while True:
                out = await er.out_queue.get()
                if out is None:
                    return toks
                toks.extend(out.token_ids)

        first = _request(PROMPTS[0], 30)
        sched.add_request(first)
        t1 = asyncio.ensure_future(collect(first))
        await asyncio.sleep(0.05)  # let the pipeline engage
        engaged = sched.pipeline_bursts
        late = _request(PROMPTS[1], 30)
        sched.add_request(late)
        t2 = asyncio.ensure_future(collect(late))
        out = [await t1, await t2]
        bursts = sched.pipeline_bursts
        await sched.stop()
        return engaged, bursts, out

    loop = asyncio.new_event_loop()
    try:
        engaged, bursts, got = loop.run_until_complete(go())
    finally:
        loop.close()
    assert engaged > 0, "pipeline never engaged before the late arrival"
    assert bursts > engaged, "pipeline never re-engaged after the drain"
    want = _streams(1, max_tokens=30)
    assert got[0] == want[0][0]
    assert got[1] == want[1][0]


def test_near_horizon_rows_fall_back_to_sync():
    """Rows within two bursts of max_model_len must decode synchronously
    (the burst would write past the block-table horizon) and still end
    with finish reason length at the same point as the sync path."""
    want = _streams(1, max_tokens=200, max_model_len=32)
    box = {}
    got = _streams(2, max_tokens=200, max_model_len=32,
                   device_finish="off", sched_out=box)
    assert got == want
    assert all(f == "length" for _, f in got)
    assert box["sched"]._inflight is None


# --------------------------------------------------------------------------
# device-resident finish detection (config.device_finish) — the
# persistent decode loop: chained bursts, frozen rows, async row drain
# --------------------------------------------------------------------------


def test_device_finish_differential_streams_identical():
    """Streams must be byte-identical with device-finish on vs off —
    token ids, logprob carriers, finish reasons — and the chained path
    must actually engage: bursts dispatched between host barriers > 1
    (the host barrier is no longer per burst)."""
    want = _streams(1)
    off_box, on_box = {}, {}
    off = _streams(2, device_finish="off", sched_out=off_box)
    on = _streams(2, sched_out=on_box)  # auto: enabled at depth 2
    assert off == want
    assert on == want
    assert off_box["sched"].runner.chained_calls == 0
    sched = on_box["sched"]
    assert sched.runner.chained_calls > 1
    assert sched._last_chain_len > 1, "host barrier still per burst"
    assert not sched._chain and not sched._chain_members
    # every finish was detected on device (all rows are device-checkable)
    assert sum(sched._device_finished_ctr.values.values()) == len(PROMPTS)


def test_device_finish_eos_mid_burst_freezes_row():
    """EOS landing mid-burst under device finish: the row freezes ON
    DEVICE at exactly the stop token (no over-decode at all — nothing
    emits after it), the stream matches the sync path byte-for-byte,
    and the reserved headroom blocks all roll back."""
    plain = _streams(1, max_tokens=24)
    eos = [plain[0][0][5]]  # lands mid-burst at K=4
    want = _streams(1, max_tokens=24, eos=eos)
    assert want[0][1] == "eos" and len(want[0][0]) <= 6
    box = {}
    got = _streams(2, max_tokens=24, eos=eos, sched_out=box)
    assert got == want
    sched = box["sched"]
    assert sched.runner.chained_calls > 0
    assert sum(sched._device_finished_ctr.values.values()) >= 1
    assert sched.allocator.used == 0  # headroom + rollback leak nothing


def test_device_finish_max_tokens_at_burst_boundary():
    """max_tokens an exact multiple of K: the LENGTH finish lands on the
    last step of a burst — the device mask must freeze the row there
    (not one burst late) and the stream must match the sync path."""
    for mt in (8, 12):  # K=4 boundaries
        want = _streams(1, max_tokens=mt)
        box = {}
        got = _streams(2, max_tokens=mt, sched_out=box)
        assert got == want
        assert all(len(toks) == mt and f == "length" for toks, f in got)
        assert box["sched"].runner.chained_calls > 0
        assert box["sched"].allocator.used == 0


def test_stop_string_rows_forced_to_sync_path():
    """Stop STRINGS need the backend's host-side post-check (the jail) —
    such rows are classified not-device-checkable at admission and the
    chain must never engage; the PR 3 per-burst-reconciled pipeline
    serves them instead, with the stream unchanged."""
    config = _config(2)

    def reqs():
        out = []
        for p in PROMPTS:
            req = PreprocessedRequest(
                token_ids=list(p),
                stop_conditions=StopConditions(max_tokens=12, ignore_eos=True,
                                               stop=["never-matches"]),
                sampling_options=SamplingOptions(temperature=0.0),
                eos_token_ids=[],
            )
            out.append(EngineRequest(
                request_id=uuid.uuid4().hex, prompt=list(p), req=req,
                ctx=AsyncEngineContext(), out_queue=asyncio.Queue(),
            ))
        return out
    rs = reqs()
    assert all(not er.device_checkable for er in rs)
    box = {}

    def grab(s):
        box["sched"] = s

    got = _run(config, rs, hooks=grab)
    sched = box["sched"]
    assert sched.runner.chained_calls == 0, "chained a stop-string row"
    assert sched.pipeline_bursts > 0, "PR 3 pipeline should still engage"
    want = _run(_config(1), reqs())
    assert got == want


def test_preemption_kv_oom_drains_chain_before_membership_changes():
    """KV OOM mid-chain must run the chain barrier (every queued burst
    reconciled, membership compacted) before preemption touches any
    row — and the resumed streams still match the unconstrained run."""
    want = _streams(1, max_tokens=24, num_kv_blocks=64)

    preempts = []

    def hook(sched):
        orig = sched._preempt

        def spy(er):
            assert not sched._chain, "preempted with chained bursts in flight"
            assert not sched._chain_members, \
                "preempted before the chain membership barrier"
            assert sched._inflight is None
            preempts.append(er.request_id)
            orig(er)

        sched._preempt = spy

    config = _config(2, num_kv_blocks=10)
    reqs = [_request(p, 24) for p in PROMPTS]
    box = {}

    def hooks(s):
        box["sched"] = s
        hook(s)

    got = _run(config, reqs, hooks=hooks)
    assert preempts, "test is vacuous: no preemption happened"
    assert box["sched"].runner.chained_calls > 0, "chain never engaged"
    assert got == want


def test_device_finish_near_horizon_rows_stay_chained():
    """Under device finish, rows near max_model_len do NOT fall back to
    sync (the PR 3 behavior): the device's LENGTH check (pos + 2 >=
    max_model_len — the in-scan mirror of _check_finish's context_len +
    1 bound) freezes them at exactly the horizon, headroom reservation
    caps at max_model_len - 1, and the streams still match the sync
    path byte-for-byte."""
    want = _streams(1, max_tokens=200, max_model_len=32)
    box = {}
    got = _streams(2, max_tokens=200, max_model_len=32, sched_out=box)
    assert got == want
    assert all(f == "length" for _, f in got)
    sched = box["sched"]
    assert sched.runner.chained_calls > 0, \
        "near-horizon rows forced sync under device finish"
    # every LENGTH finish at the horizon was detected on device
    assert sum(sched._device_finished_ctr.values.values()) == len(PROMPTS)
    assert sched.allocator.used == 0
    assert not sched._chain and not sched._chain_members


def test_late_drain_retro_invalidation_rolls_back_blocks():
    """The chain reserves block headroom against its own dispatch count,
    so a row finishing deep into a chain holds blocks covering positions
    it froze before reaching — the drain's retro-invalidation must roll
    that tail back into the allocator (rollback_tail observed with a
    shrinking keep) and leak nothing."""
    rollbacks = []

    def hook(sched):
        orig = sched.allocator.rollback_tail

        def spy(block_ids, keep):
            rollbacks.append((len(block_ids), keep))
            return orig(block_ids, keep)

        sched.allocator.rollback_tail = spy

    plain = _streams(1, max_tokens=24)
    eos = [plain[2][0][2]]  # row 2 stops early, deep headroom reserved
    config = _config(2, num_kv_blocks=64)
    reqs = [_request(p, 24, eos=eos) for p in PROMPTS]
    box = {}

    def hooks(s):
        box["sched"] = s
        hook(s)

    _run(config, reqs, hooks=hooks)
    sched = box["sched"]
    assert sched.runner.chained_calls > 0
    assert any(total > keep for total, keep in rollbacks), \
        "no over-reserved tail was ever rolled back"
    assert sched.allocator.used == 0


# --------------------------------------------------------------------------
# device-time attribution on the chained path (telemetry/device_time.py)
# --------------------------------------------------------------------------


def _run_chained(with_tracker):
    """Drive the persistent loop over a FakeRunner, spying on the host
    syncs (_observe_host_sync — every executor-side device sync passes
    through it). Returns (streams, sync_count, tracker_or_None)."""
    from dynamo_tpu.telemetry.device_time import DeviceTimeTracker

    config = _config(2)  # device_finish auto → on at depth 2
    reqs = [_request(p, 21) for p in PROMPTS]
    syncs = []
    box = {}

    async def go():
        runner = FakeRunner(config)
        tracker = None
        if with_tracker:
            tracker = DeviceTimeTracker(
                param_bytes=1e9, kv_bytes_per_token=1e3, hbm_gbps=100.0,
            )
            runner.device_time = tracker
        sched = Scheduler(runner, config)
        box["sched"] = sched
        orig = sched._observe_host_sync

        def spy(dt):
            syncs.append(dt)
            orig(dt)

        sched._observe_host_sync = spy
        sched.start()

        async def collect(er):
            toks, finish = [], None
            while True:
                out = await er.out_queue.get()
                if out is None:
                    return toks, finish
                toks.extend(out.token_ids)
                if out.finish_reason is not None:
                    finish = out.finish_reason
        try:
            for er in reqs:
                sched.add_request(er)
            return await asyncio.gather(*(collect(er) for er in reqs)), tracker
        finally:
            await sched.stop()

    loop = asyncio.new_event_loop()
    try:
        streams, tracker = loop.run_until_complete(go())
    finally:
        loop.close()
    return streams, len(syncs), tracker


def test_device_time_chained_adds_no_host_syncs_and_attributes_bursts():
    """The device-time tracker measures off the async drain's EXISTING
    reconciliation seams: with it attached, the chained path performs
    exactly the same number of host syncs, the streams are byte-
    identical, and every chained burst lands as a decode_burst_df
    observation with nonzero busy time + a live roofline fraction."""
    base_streams, base_syncs, _ = _run_chained(with_tracker=False)
    streams, syncs, tracker = _run_chained(with_tracker=True)
    assert streams == base_streams
    assert syncs == base_syncs, "device-time tracking added a host sync"
    assert tracker is not None and tracker.observations > 0
    assert tracker.busy_s.get("decode", 0.0) > 0.0
    assert box_chained_calls(tracker) > 0
    text = tracker.registry.render()
    assert "dynamo_engine_device_time_seconds" in text
    assert "dynamo_engine_roofline_fraction" in text
    ((_, frac),) = tracker._roofline()
    assert frac > 0.0
    # the chained program is what got attributed (alongside the prefill)
    programs = {dict(k).get("program") for k in tracker._time_hist.counts}
    assert "decode_burst_df" in programs
    phases = {dict(k).get("phase") for k in tracker._time_hist.counts}
    assert phases <= {"decode", "prefill"}


def box_chained_calls(tracker):
    # decode tokens accumulated via the burst token accounting
    return tracker.decode_tokens


# --------------------------------------------------------------------------
# unrestricted persistent decode (ISSUE 13): guided / stop-string / n>1 /
# spec traffic inside the chain, with the sync-fallback ladder counted
# --------------------------------------------------------------------------


def _fallback_reasons(sched):
    return {dict(k).get("reason") for k in
            sched._sync_fallback_ctr.values}


def _guided_request(prompt, max_tokens, choice_ids):
    return _request(prompt, max_tokens, sampling=SamplingOptions(
        temperature=0.0, guided_choice_token_ids=choice_ids,
    ))


# two long choices sharing a 4-token prefix so the chain runs >1 burst
CHOICES = [
    [7, 11, 13, 17, 19, 23, 29, 31, 37, 41, 43, 47],
    [7, 11, 13, 17, 100, 101, 102, 103, 104, 105, 106, 107],
]


def _precompile_guided_tables(sched):
    """Deterministic table availability for chain-engagement asserts:
    compile synchronously (the production path compiles in an executor
    and serves sync passes until the table lands)."""
    orig_reason = sched._guided_chain_reason

    def eager(er):
        key = sched._guided_table_key(er)
        if key not in sched._guided_tables:
            sched._guided_tables[key] = sched._compile_guided_table(er)
        return orig_reason(er)

    sched._guided_chain_reason = eager


def test_guided_choice_chains_byte_identical():
    """guided_choice rows now chain through the device transition
    table: streams byte-identical to the host-mask sync path, chain
    length > 1, the guided finish detected on device, zero leaked
    blocks."""
    config_sync = _config(1, k=2)
    want = _run(config_sync, [_guided_request([1, 2], 16, CHOICES)])
    assert want[0][1] == "stop" and list(want[0][0]) in CHOICES
    box = {}

    def hooks(s):
        box["sched"] = s
        _precompile_guided_tables(s)

    got = _run(_config(2, k=2), [_guided_request([1, 2], 16, CHOICES)],
               hooks=hooks)
    assert got == want
    sched = box["sched"]
    assert sched.runner.chained_calls > 1, "guided chain never engaged"
    assert sched._last_chain_len > 1
    assert sum(sched._device_finished_ctr.values.values()) == 1
    assert sched.allocator.used == 0


def test_guided_json_in_bound_chains_byte_identical():
    """An in-bound guided_json grammar (tiny enum schema over a toy
    piece table) chains through its compiled table and the stream
    matches the sync path byte-for-byte."""
    from dynamo_tpu.engine.guided import JsonConstraint, JsonGrammar

    v = 512
    pieces = [None] * v
    for i, ch in enumerate('"abcdefgh'):
        pieces[50 + i] = ch
    grammar = JsonGrammar(
        pieces, {"enum": ["abca", "abda", "aeee", "gh"]}
    )

    def reqs():
        er = _request([1, 2], 16)
        er.guided = JsonConstraint(grammar)
        return [er]

    want = _run(_config(1, k=2), reqs())
    assert want[0][1] == "stop" and len(want[0][0]) >= 4
    box = {}

    def hooks(s):
        box["sched"] = s
        _precompile_guided_tables(s)

    got = _run(_config(2, k=2), reqs(), hooks=hooks)
    assert got == want
    sched = box["sched"]
    assert sched.runner.chained_calls > 1, "guided-json chain never engaged"
    assert sched.allocator.used == 0


def test_guided_table_bound_falls_back_named():
    """A grammar whose reachable states exceed the bound keeps the sync
    path with reason guided_table_bound — never a silent downgrade."""
    config = _config(2, k=2, guided_table_max_states=2)
    box = {}

    def hooks(s):
        box["sched"] = s
        _precompile_guided_tables(s)

    out = _run(config, [_guided_request([1, 2], 16, CHOICES)],
               hooks=hooks)
    sched = box["sched"]
    assert out[0][1] == "stop"
    assert sched.runner.chained_calls == 0
    assert "guided_table_bound" in _fallback_reasons(sched)


def _stop_seq_request(prompt, max_tokens, seqs, stop=None):
    req = PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions=StopConditions(
            max_tokens=max_tokens, ignore_eos=True,
            stop=stop or ["x"] * len(seqs),
            stop_token_seqs=[list(s) for s in seqs],
        ),
        sampling_options=SamplingOptions(temperature=0.0),
        eos_token_ids=[],
    )
    return EngineRequest(
        request_id=uuid.uuid4().hex, prompt=list(prompt), req=req,
        ctx=AsyncEngineContext(), out_queue=asyncio.Queue(),
    )


def test_stop_string_token_seq_chains_byte_identical():
    """Stop-string rows with canonical token seqs chain via the
    suffix-hash approximation: the device freezes the row at the
    matching token, the host's exact check names the STOP, and the
    stream matches the sync path (which runs the same exact check)."""
    plain = _streams(1, max_tokens=24)
    seq = [plain[0][0][3], plain[0][0][4]]  # tokens 4-5 of the stream

    def reqs():
        return [_stop_seq_request(PROMPTS[0], 24, [seq])]

    rs = reqs()
    assert all(er.device_checkable for er in rs)
    want = _run(_config(1), rs)
    assert want[0][1] == "stop" and len(want[0][0]) == 5
    box = {}

    def grab(s):
        box["sched"] = s

    got = _run(_config(2), reqs(), hooks=grab)
    assert got == want
    sched = box["sched"]
    assert sched.runner.chained_calls > 0, "stop-seq row never chained"
    assert sum(sched._device_finished_ctr.values.values()) == 1
    assert sched.allocator.used == 0


def test_stop_string_false_positive_resumes_byte_identical():
    """A suffix-hash collision (injected via the fake's candidate hook)
    freezes a row the host cannot confirm: the scheduler must flag the
    false positive, close the chain, and resume the row so the stream
    is STILL byte-identical to the sync path — with zero leaked blocks
    and the fallback counter naming stop_false_positive."""
    never = [499, 498]  # a seq the stream never produces

    def reqs():
        return [_stop_seq_request(p, 21, [never]) for p in PROMPTS]

    want = _run(_config(1), reqs())
    assert all(f == "length" for _, f in want)
    box = {}
    fired = []

    def hooks(s):
        box["sched"] = s

        def force(slot, gen):
            if slot == 0 and gen == 6 and not fired:
                fired.append((slot, gen))
                return True
            return False

        s.runner.force_stop_candidate = force

    got = _run(_config(2), reqs(), hooks=hooks)
    assert fired, "test is vacuous: the candidate hook never fired"
    assert got == want
    sched = box["sched"]
    assert sched.runner.chained_calls > 1
    assert "stop_false_positive" in _fallback_reasons(sched)
    assert sched.allocator.used == 0, "false-positive path leaked blocks"


def test_stop_ids_width_16_chains_and_overflow_is_named():
    """9-16 stop/eos ids chain now (the old width-8 cliff); >16 fall
    back with reason stop_ids_overflow instead of silently."""
    plain = _streams(1, max_tokens=24)
    eos16 = [plain[0][0][5]] + list(range(400, 415))  # 16 ids, one hits
    assert len(eos16) == 16
    want = _streams(1, max_tokens=24, eos=eos16)
    assert want[0][1] == "eos"
    box = {}
    got = _streams(2, max_tokens=24, eos=eos16, sched_out=box)
    assert got == want
    assert box["sched"].runner.chained_calls > 0, "16-id row never chained"

    eos17 = list(range(400, 417))
    rs = [_request(PROMPTS[0], 8, eos=eos17)]
    assert not rs[0].device_checkable
    assert rs[0].chain_fallback == "stop_ids_overflow"
    box2 = {}

    def grab(s):
        box2["sched"] = s

    _run(_config(2), rs, hooks=grab)
    assert "stop_ids_overflow" in _fallback_reasons(box2["sched"])


def test_n_gt_1_fans_out_into_chain_members():
    """serving-level n>1 fan-out: each choice is an independent n=1
    chain member; deltas fold at drain tagged with their choice index,
    per-choice streams match n separate single-choice runs, and the
    chain engages (depth 2)."""
    from dynamo_tpu.engine.serving import JaxServingEngine
    from dynamo_tpu.runtime.engine import Context

    def fan_run(depth):
        config = _config(depth)

        async def go():
            runner = FakeRunner(config)
            sched = Scheduler(runner, config)
            engine = JaxServingEngine(runner, sched, config)
            sched.start()
            req = PreprocessedRequest(
                token_ids=[1, 17, 43],
                stop_conditions=StopConditions(max_tokens=9,
                                               ignore_eos=True),
                sampling_options=SamplingOptions(temperature=0.0, n=3),
                eos_token_ids=[],
            )
            per_choice = {i: [] for i in range(3)}
            finishes = {}
            async for out in engine.generate(Context(req)):
                c = out.get("choice")
                per_choice[c].extend(out.get("token_ids", []))
                if out.get("finish_reason"):
                    finishes[c] = out["finish_reason"]
            chained = sched.runner.chained_calls
            await engine.close()
            return per_choice, finishes, chained

        loop = asyncio.new_event_loop()
        try:
            return loop.run_until_complete(go())
        finally:
            loop.close()

    single = _run(_config(1), [_request([1, 17, 43], 9)])
    sync_c, sync_f, _ = fan_run(1)
    chain_c, chain_f, chained = fan_run(2)
    assert chain_c == sync_c
    assert chain_f == sync_f == {0: "length", 1: "length", 2: "length"}
    # greedy choices are identical streams, each equal to a lone run
    for i in range(3):
        assert chain_c[i] == single[0][0]
    assert chained > 1, "n>1 children never chained"


def test_mixed_workload_chains_with_attributed_fallbacks():
    """The acceptance shape: a mixed batch (plain + guided + stop-seq)
    runs with chain length p50 > 1 and every sync pass attributed to a
    named reason in dynamo_engine_sync_fallback_total."""
    plain = _streams(1, max_tokens=20)
    seq = [plain[1][0][4], plain[1][0][5]]

    def reqs():
        return [
            _request(PROMPTS[0], 20),
            _stop_seq_request(PROMPTS[1], 20, [seq]),
            _guided_request(PROMPTS[2], 20, CHOICES),
        ]

    want = _run(_config(1, k=2), reqs())
    box = {}

    def hooks(s):
        box["sched"] = s
        _precompile_guided_tables(s)

    got = _run(_config(2, k=2), reqs(), hooks=hooks)
    assert got == want
    sched = box["sched"]
    assert sched._last_chain_len > 1 or sched._chain_dispatched > 1
    assert sched.runner.chained_calls > 1
    # every counted fallback reason is named (no empty labels)
    assert all(r for r in _fallback_reasons(sched))
    assert sched.allocator.used == 0
