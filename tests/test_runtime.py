"""Distributed runtime tests: in-memory hub and real dynstore TCP server."""

import asyncio

import msgpack
import pytest

from dynamo_tpu.runtime import (
    Context,
    DistributedRuntime,
    EngineError,
    NoInstancesError,
    ResponseStreamError,
    RouterMode,
)
from dynamo_tpu.runtime.client import Client
from dynamo_tpu.runtime.transports.memory import MemoryHub
from dynamo_tpu.runtime.transports.dynstore import DynStoreServer


async def echo_handler(payload, ctx):
    for tok in payload["text"].split():
        yield {"tok": tok}


async def slow_handler(payload, ctx):
    for i in range(1000):
        if ctx.is_stopped:
            yield {"done": "stopped"}
            return
        yield {"i": i}
        await asyncio.sleep(0.005)


async def failing_handler(payload, ctx):
    raise EngineError("model not loaded")
    yield  # pragma: no cover


def make_drt():
    return DistributedRuntime.in_process(MemoryHub())


@pytest.mark.asyncio
async def test_roundtrip_in_memory():
    drt = make_drt()
    ep = drt.namespace("test").component("worker").endpoint("generate")
    serving = await ep.serve(echo_handler)
    client = await Client(ep).start()
    await client.wait_for_instances(1)

    out = []
    async for item in client.generate(Context({"text": "hello tpu world"})):
        out.append(item["tok"])
    assert out == ["hello", "tpu", "world"]
    await serving.stop()
    await drt.close()


@pytest.mark.asyncio
async def test_round_robin_across_instances():
    drt = make_drt()
    ns = drt.namespace("test")
    ep = ns.component("worker").endpoint("gen")

    hits = {"a": 0, "b": 0}

    def make(name):
        async def h(payload, ctx):
            hits[name] += 1
            yield {"from": name}
        return h

    s1 = await ep.serve(make("a"), instance_id="ia")
    s2 = await ep.serve(make("b"), instance_id="ib")
    client = await Client(ep, RouterMode.ROUND_ROBIN).start()
    await client.wait_for_instances(2)

    for _ in range(6):
        async for _item in client.generate(Context({})):
            pass
    assert hits == {"a": 3, "b": 3}

    # direct routing
    rec = await client.direct({}, "ia")
    async for _ in rec:
        pass
    assert hits["a"] == 4
    await s1.stop(); await s2.stop(); await drt.close()


@pytest.mark.asyncio
async def test_no_instances_error():
    drt = make_drt()
    ep = drt.namespace("t").component("c").endpoint("e")
    client = await Client(ep).start()
    with pytest.raises(NoInstancesError):
        async for _ in client.generate(Context({})):
            pass
    await drt.close()


@pytest.mark.asyncio
async def test_engine_error_surfaces_in_prologue():
    drt = make_drt()
    ep = drt.namespace("t").component("c").endpoint("e")
    serving = await ep.serve(failing_handler)
    client = await Client(ep).start()
    await client.wait_for_instances(1)
    with pytest.raises(ResponseStreamError, match="model not loaded"):
        async for _ in client.generate(Context({})):
            pass
    await serving.stop(); await drt.close()


@pytest.mark.asyncio
async def test_stop_generating_propagates():
    drt = make_drt()
    ep = drt.namespace("t").component("c").endpoint("e")
    serving = await ep.serve(slow_handler)
    client = await Client(ep).start()
    await client.wait_for_instances(1)

    ctx_req = Context({})
    received = []
    async for item in client.generate(ctx_req):
        received.append(item)
        if len(received) == 3:
            ctx_req.context.stop_generating()
    assert received[-1] == {"done": "stopped"}
    assert len(received) < 100
    await serving.stop(); await drt.close()


@pytest.mark.asyncio
async def test_lease_expiry_removes_instance():
    hub = MemoryHub()
    drt = DistributedRuntime.in_process(hub)
    ep = drt.namespace("t").component("c").endpoint("e")
    serving = await ep.serve(echo_handler)
    client = await Client(ep).start()
    await client.wait_for_instances(1)
    assert len(client.instances) == 1

    lease = await drt.discovery.primary_lease()
    hub.expire_lease(lease.id)  # simulate worker death
    await asyncio.sleep(0.01)
    assert len(client.instances) == 0
    await serving.stop(); await drt.close()


@pytest.mark.asyncio
async def test_work_queue_ack_and_redelivery():
    drt = make_drt()
    m = drt.messaging
    await m.queue_push("q", b"job1")
    item = await m.queue_pop("q", timeout=1.0, visibility=0.05)
    assert item.payload == b"job1"
    # no ack → redelivered after visibility timeout
    await asyncio.sleep(0.1)
    item2 = await m.queue_pop("q", timeout=1.0, visibility=0.05)
    assert item2.payload == b"job1"
    item2.ack()
    await asyncio.sleep(0.1)
    assert await m.queue_depth("q") == 0
    await drt.close()


@pytest.mark.asyncio
async def test_stats_scrape():
    drt = make_drt()
    ep = drt.namespace("t").component("c").endpoint("e")
    serving = await ep.serve(echo_handler, stats_handler=lambda: {"load": 0.5})
    client = await Client(ep).start()
    await client.wait_for_instances(1)
    async for _ in client.generate(Context({"text": "x"})):
        pass
    stats = await client.scrape_stats()
    assert len(stats) == 1
    info = next(iter(stats.values()))
    assert info["requests_total"] == 1
    assert info["data"] == {"load": 0.5}
    await serving.stop(); await drt.close()


# ---------- dynstore: real TCP server, multi-"process" style clients ----------


@pytest.mark.asyncio
async def test_dynstore_end_to_end():
    server = DynStoreServer(port=0)
    await server.start()
    try:
        worker_drt = await DistributedRuntime.connect(port=server.port)
        client_drt = await DistributedRuntime.connect(port=server.port)

        ep_w = worker_drt.namespace("prod").component("w").endpoint("gen")
        serving = await ep_w.serve(echo_handler)

        ep_c = client_drt.namespace("prod").component("w").endpoint("gen")
        client = await Client(ep_c).start()
        await client.wait_for_instances(1)

        out = []
        async for item in client.generate(Context({"text": "over real tcp"})):
            out.append(item["tok"])
        assert out == ["over", "real", "tcp"]

        # kv + watch
        await client_drt.discovery.kv_put("cfg/threshold", b"123")
        assert await worker_drt.discovery.kv_get("cfg/threshold") == b"123"

        # pub/sub across clients
        sub = await worker_drt.messaging.subscribe("events.kv")
        await client_drt.messaging.publish("events.kv", b"stored")
        msg = await asyncio.wait_for(sub.__anext__(), 2.0)
        assert msg.payload == b"stored"

        # work queue across clients
        await client_drt.messaging.queue_push("prefill", b"req-1")
        item = await worker_drt.messaging.queue_pop("prefill", timeout=2.0)
        assert item.payload == b"req-1"
        item.ack()

        await serving.stop()
        await worker_drt.close()
        await client_drt.close()
    finally:
        await server.stop()


@pytest.mark.asyncio
async def test_dynstore_conn_drop_expires_lease():
    server = DynStoreServer(port=0)
    await server.start()
    try:
        worker_drt = await DistributedRuntime.connect(port=server.port)
        watcher_drt = await DistributedRuntime.connect(port=server.port)

        ep = worker_drt.namespace("p").component("w").endpoint("g")
        await ep.serve(echo_handler)

        ep2 = watcher_drt.namespace("p").component("w").endpoint("g")
        client = await Client(ep2).start()
        await client.wait_for_instances(1)

        # hard-kill the worker's connection (process death — disable the
        # reconnect layer, which would otherwise resurrect the instance)
        worker_drt.discovery.reconnect = False
        worker_drt.discovery._writer.close()
        await asyncio.sleep(0.3)
        assert len(client.instances) == 0
        await watcher_drt.close()
    finally:
        await server.stop()


@pytest.mark.asyncio
async def test_dynstore_broker_restart_graph_keeps_serving():
    """Kill and restart the broker under an active graph: the clients
    reconnect with backoff, re-grant leases, re-register endpoints, re-arm
    watches/subscriptions, and requests flow again (reference analog: etcd
    lease re-establishment, lib/runtime/src/transports/etcd/lease.rs)."""
    server = DynStoreServer(port=0)
    await server.start()
    port = server.port
    worker_drt = await DistributedRuntime.connect(port=port)
    client_drt = await DistributedRuntime.connect(port=port)
    try:
        ep_w = worker_drt.namespace("prod").component("w").endpoint("gen")
        serving = await ep_w.serve(echo_handler)
        ep_c = client_drt.namespace("prod").component("w").endpoint("gen")
        client = await Client(ep_c).start()
        await client.wait_for_instances(1)

        sub = await client_drt.messaging.subscribe("events.test")

        out = [t["tok"] async for t in client.generate(Context({"text": "before restart"}))]
        assert out == ["before", "restart"]

        # broker dies and comes back on the same port
        await server.stop()
        await asyncio.sleep(0.2)
        server = DynStoreServer(port=port)
        await server.start()

        # worker re-registers under the SAME instance id (stable client
        # lease handle) and the client's re-armed watch re-discovers it
        await client.wait_for_instances(1)
        out = [t["tok"] async for t in client.generate(Context({"text": "after restart"}))]
        assert out == ["after", "restart"]

        # re-armed subscription still delivers
        await worker_drt.messaging.publish("events.test", b"again")
        msg = await asyncio.wait_for(sub.__anext__(), 5.0)
        assert msg.payload == b"again"

        # work queue usable through the new broker
        await client_drt.messaging.queue_push("q2", b"job")
        item = await worker_drt.messaging.queue_pop("q2", timeout=2.0)
        assert item.payload == b"job"
        item.ack()

        await serving.stop()
    finally:
        await worker_drt.close()
        await client_drt.close()
        await server.stop()
