"""Request X-ray: cluster-stitched traces, device-time/roofline
attribution, SLO goodput — unit coverage for telemetry/{stitch,
device_time,slo}.py and the trace-store bounds, plus the cross-process
e2e (frontend → decode worker → prefill worker on one timeline)."""

import asyncio
import json
import os
import subprocess
import sys

import pytest

from dynamo_tpu.telemetry.device_time import DeviceTimeTracker
from dynamo_tpu.telemetry.registry import MetricsRegistry
from dynamo_tpu.telemetry.slo import SloTracker
from dynamo_tpu.telemetry.stitch import (
    estimate_offset,
    estimate_offset_return_leg,
    remote_span_set,
    stitched_timeline,
    timeline_gaps,
)
from dynamo_tpu.telemetry.tracing import TraceRecorder

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --------------------------------------------------------------------------
# clock-offset estimation (injected skew)
# --------------------------------------------------------------------------


def test_offset_recovered_under_symmetric_legs():
    """Remote clock 500 s ahead, symmetric 10 ms legs, a LONG remote
    hold (37 s): the offset estimate is exact — remote processing time
    drops out of the NTP formula entirely."""
    skew = 500.0
    sent = 1000.0
    recv_remote = sent + 0.010 + skew          # after the forward leg
    resp_sent_remote = recv_remote + 37.0      # remote held it 37 s
    resp_recv_local = sent + 0.010 + 37.0 + 0.010
    offset, rtt = estimate_offset(
        sent, recv_remote, resp_sent_remote, resp_recv_local)
    assert offset == pytest.approx(skew, abs=1e-9)
    assert rtt == pytest.approx(0.020, abs=1e-9)


def test_offset_error_bounded_by_half_rtt():
    """Fully asymmetric legs (all 80 ms on the forward leg): the error
    is exactly rtt/2 — the documented confidence bound."""
    skew = -123.0
    sent = 50.0
    recv_remote = sent + 0.080 + skew
    resp_sent_remote = recv_remote + 1.0
    resp_recv_local = sent + 0.080 + 1.0  # return leg instantaneous
    offset, rtt = estimate_offset(
        sent, recv_remote, resp_sent_remote, resp_recv_local)
    assert rtt == pytest.approx(0.080, abs=1e-9)
    assert abs(offset - skew) == pytest.approx(rtt / 2, abs=1e-9)


def test_negative_apparent_rtt_clamps_to_zero():
    # skewed stamps can make the apparent rtt negative; never propagate it
    _, rtt = estimate_offset(10.0, 5.0, 6.0, 10.5)
    assert rtt == 0.0


def test_queued_forward_offset_immune_to_queue_wait():
    """The remote-prefill hop's forward "leg" is a queue submit: a 4 s
    backlog must NOT skew the offset by ~2 s (the symmetric formula
    would). queued_forward estimates from the commit return leg alone —
    error bounded by the one-way commit transit, not the queue wait."""
    skew = 77.0
    submit = 1000.0
    dequeue_remote = submit + 4.0 + skew        # 4 s queue backlog
    commit_sent_remote = dequeue_remote + 2.5   # prefill compute
    commit_recv_local = submit + 4.0 + 2.5 + 0.004  # 4 ms commit transit
    # symmetric formula: half the queue wait lands in the estimate
    sym, _ = estimate_offset(
        submit, dequeue_remote, commit_sent_remote, commit_recv_local)
    assert abs(sym - skew) > 1.9
    # return-leg-only: error is exactly the one-way commit transit
    one_way = estimate_offset_return_leg(
        commit_sent_remote, commit_recv_local)
    assert one_way == pytest.approx(skew - 0.004, abs=1e-9)
    # remote_span_set(queued_forward=True) folds with the good estimate
    rs = remote_span_set(
        "prefill_worker", [["prefill.dequeue", dequeue_remote]],
        recv_at=dequeue_remote, resp_sent_at=commit_sent_remote,
        sent_local=submit, resp_recv_local=commit_recv_local,
        queued_forward=True,
    )
    assert rs["offset_s"] == pytest.approx(skew - 0.004, abs=1e-6)
    # the dequeue span renders at its TRUE local-axis position (~4 s in)
    tl = stitched_timeline({
        "request_id": "r", "t0_wall": submit, "spans": [], "remote": [rs],
    })
    (row,) = tl["timeline"]
    assert row["start_s"] == pytest.approx(4.0, abs=0.01)


# --------------------------------------------------------------------------
# stitched timeline (skewed sources, nested hops, gaps)
# --------------------------------------------------------------------------


def _trace_with_remote(skew_worker=1000.0, skew_prefill=2000.0):
    """A synthetic frontend trace + a decode-worker hop (clock +1000 s)
    that itself holds a prefill-worker hop (clock +2000 s vs frontend,
    i.e. +1000 s vs the worker). True frontend-axis times: worker spans
    at 0.10/0.20, prefill spans at 0.12/0.18."""
    t0 = 10_000.0
    worker = remote_span_set(
        "decode_engine",
        [["admission", t0 + 0.10 + skew_worker],
         ["completion", t0 + 0.20 + skew_worker]],
        recv_at=t0 + 0.05 + skew_worker,
        resp_sent_at=t0 + 0.21 + skew_worker,
        sent_local=t0 + 0.05, resp_recv_local=t0 + 0.21,
        children=[remote_span_set(
            "prefill_worker",
            [["prefill.dequeue", t0 + 0.12 + skew_prefill],
             ["prefill.compute", t0 + 0.18 + skew_prefill]],
            recv_at=t0 + 0.11 + skew_prefill,
            resp_sent_at=t0 + 0.19 + skew_prefill,
            # the worker folded this child against ITS clock
            sent_local=t0 + 0.11 + skew_worker,
            resp_recv_local=t0 + 0.19 + skew_worker,
        )],
    )
    return {
        "request_id": "r1", "model": "m", "status": "success",
        "total_s": 0.25, "t0_wall": t0,
        "spans": [
            {"name": "http", "offset_s": 0.0, "duration_s": 0.0},
            {"name": "first_token", "offset_s": 0.0, "duration_s": 0.22},
            {"name": "egress", "offset_s": 0.22, "duration_s": 0.03},
        ],
        "remote": [worker],
    }


def test_stitched_timeline_renders_skewed_sources_on_one_axis():
    stitched = stitched_timeline(_trace_with_remote())
    by_source = {}
    for row in stitched["timeline"]:
        by_source.setdefault(row["source"], []).append(row)
    assert set(by_source) == {"frontend", "decode_engine", "prefill_worker"}
    # the worker's completion span: starts at its admission mark (0.10
    # on the frontend axis, the 1000 s skew fully corrected) and runs
    # to 0.20
    comp = [r for r in by_source["decode_engine"]
            if r["name"] == "completion"][0]
    assert comp["start_s"] == pytest.approx(0.10, abs=1e-3)
    assert comp["duration_s"] == pytest.approx(0.10, abs=1e-3)
    # the nested prefill hop composes BOTH offsets (frontend→worker→
    # prefill): its compute span sits at 0.12..0.18 on the same axis
    pcomp = [r for r in by_source["prefill_worker"]
             if r["name"] == "prefill.compute"][0]
    assert pcomp["start_s"] == pytest.approx(0.12, abs=1e-3)
    assert pcomp["duration_s"] == pytest.approx(0.06, abs=1e-3)
    # per-hop confidence metadata is present for every source
    assert {s["source"] for s in stitched["sources"]} == {
        "frontend", "decode_engine", "prefill_worker"}


def test_timeline_gaps_attribute_uncovered_time():
    timeline = [
        {"source": "frontend", "name": "http", "start_s": 0.0,
         "duration_s": 0.01},
        {"source": "decode_engine", "name": "prefill", "start_s": 0.5,
         "duration_s": 0.1},
    ]
    gaps = timeline_gaps(timeline, min_gap_s=0.001)
    assert len(gaps) == 1
    assert gaps[0]["start_s"] == pytest.approx(0.01)
    assert gaps[0]["duration_s"] == pytest.approx(0.49)
    assert gaps[0]["after"] == "frontend:http"
    assert gaps[0]["before"] == "decode_engine:prefill"


def test_stitch_depth_is_bounded():
    """A hostile/buggy frame cannot recurse the stitcher to death."""
    inner = {"source": "w", "spans": [["x", 1.0]], "offset_s": 0.0,
             "rtt_s": 0.0, "children": []}
    for _ in range(40):
        inner = {"source": "w", "spans": [], "offset_s": 0.0,
                 "rtt_s": 0.0, "children": [inner]}
    trace = {"t0_wall": 0.0, "spans": [], "remote": [inner]}
    stitched = stitched_timeline(trace)  # must terminate
    assert all(r["name"] != "x" for r in stitched["timeline"])


# --------------------------------------------------------------------------
# trace store bounds: TTL + max-entries LRU, evictions counted
# --------------------------------------------------------------------------


def _recorder(**kw):
    reg = MetricsRegistry()
    clock = {"t": 0.0}
    rec = TraceRecorder(registry=reg, clock=lambda: clock["t"], **kw)
    return rec, reg, clock


def test_trace_store_capacity_lru_evicts_and_counts():
    rec, reg, _ = _recorder(capacity=3, ttl_s=0)
    for i in range(5):
        rec.record(f"r{i}", "m", "success", [("http", float(i))], end=float(i))
    assert len(rec) == 3
    assert rec.get("r0") is None and rec.get("r1") is None
    assert rec.get("r4") is not None
    assert rec.evicted == 2
    assert 'dynamo_trace_evicted_total{reason="capacity"} 2.0' in reg.render()


def test_trace_store_ttl_expires_and_counts():
    rec, reg, clock = _recorder(capacity=100, ttl_s=10.0)
    rec.record("old", "m", "success", [("http", 0.0)], end=0.0)
    clock["t"] = 5.0
    rec.record("mid", "m", "success", [("http", 0.0)], end=0.0)
    clock["t"] = 11.0
    # "old" is 11 s stale → expired on the next touch; "mid" survives
    assert rec.get("old") is None
    assert rec.get("mid") is not None
    assert rec.evicted == 1
    assert 'dynamo_trace_evicted_total{reason="ttl"} 1.0' in reg.render()
    # the store gauge renders the live count
    assert "dynamo_trace_store_requests 1" in reg.render()


def test_trace_store_ttl_zero_disables_age_eviction():
    rec, _, clock = _recorder(capacity=100, ttl_s=0)
    rec.record("r", "m", "success", [("http", 0.0)], end=0.0)
    clock["t"] = 1e9
    assert rec.get("r") is not None


# --------------------------------------------------------------------------
# device-time tracker: serialized intervals, bubbles, roofline
# --------------------------------------------------------------------------


def _tracker(**kw):
    clock = {"t": 0.0}
    kw.setdefault("param_bytes", 1e9)
    kw.setdefault("kv_bytes_per_token", 1e3)
    kw.setdefault("hbm_gbps", 100.0)  # peak = 1e11 B/s
    t = DeviceTimeTracker(clock=lambda: clock["t"], **kw)
    return t, clock


def test_overlapping_chained_observations_serialize_not_double_count():
    """Three chained bursts dispatched back-to-back at t=0.00/0.01/0.02,
    each 0.1 s of device time, reconciled late: busy must total 0.3 s
    (the device ran them serially), not 3 × (ready − dispatch)."""
    t, _ = _tracker()
    t.observe("decode_burst_df", "decode", 0.00, 0.10)
    t.observe("decode_burst_df", "decode", 0.01, 0.20)
    t.observe("decode_burst_df", "decode", 0.02, 0.30)
    assert t.busy_s["decode"] == pytest.approx(0.30, abs=1e-9)
    assert t.bubble_s.get("decode", 0.0) == 0.0


def test_bubble_between_sync_bursts_is_charged():
    t, _ = _tracker()
    t.observe("decode", "decode", 0.0, 0.1)
    # next dispatch 50 ms after the previous ready: the device ran dry
    t.observe("decode", "decode", 0.15, 0.25)
    assert t.busy_s["decode"] == pytest.approx(0.2, abs=1e-9)
    assert t.bubble_s["decode"] == pytest.approx(0.05, abs=1e-9)
    ratios = dict(
        (labels["phase"], v) for labels, v in t._busy_ratios()
    )
    assert ratios["decode"] == pytest.approx(0.2 / 0.25, abs=1e-6)


def test_idle_reset_never_charges_request_starved_wait():
    t, _ = _tracker()
    t.observe("decode", "decode", 0.0, 0.1)
    t.idle()  # queue drained; next request arrives much later
    t.observe("decode", "decode", 100.0, 100.1)
    assert t.bubble_s.get("decode", 0.0) == 0.0


def test_roofline_fraction_matches_byte_model():
    t, _ = _tracker()
    # one 8-step burst over 4 rows, 100-token contexts: bytes =
    # 8 * (1e9 + 4*100*1e3) = 8.0032e9 over 0.1 s busy → 8.0032e10 B/s
    # over the 1e11 peak = 0.80032
    rb = t.decode_read_bytes(8, 400)
    t.observe("decode_burst", "decode", 0.0, 0.1, read_bytes=rb, tokens=32)
    ((_, frac),) = t._roofline()
    assert frac == pytest.approx(0.80032, rel=1e-6)
    # and it renders on the registry as the gauge
    assert "dynamo_engine_roofline_fraction" in t.registry.render()


def test_prefill_busy_never_feeds_the_roofline():
    t, _ = _tracker()
    t.observe("prefill", "prefill", 0.0, 1.0, read_bytes=5e9)
    assert t._roofline() == []  # no decode bytes/busy yet
    assert t.busy_s["prefill"] == pytest.approx(1.0)


def test_sp_prefill_bytes_feed_the_roofline():
    """The sequence-parallel ladder's modelled bytes DO shape the gauge
    (docs/long_context.md) — program-gated, so the plain dense ladder
    above stays excluded."""
    t, _ = _tracker()
    t.observe("prefill_sp", "prefill", 0.0, 1.0, read_bytes=5e9)
    (labels, frac), = t._roofline()
    assert labels == {}
    assert frac == pytest.approx(5e9 / t.peak_bytes_per_s)
    # byte model sanity: one chunk = weights once + ctx KV written once
    one = t.sp_prefill_read_bytes(1, 100)
    assert one == pytest.approx(t.param_bytes
                                + 100 * t.kv_bytes_per_token)
    # more chunks add a triangular prefix re-read
    three = t.sp_prefill_read_bytes(3, 300)
    assert three > 3 * t.param_bytes + 300 * t.kv_bytes_per_token
    # route-parameterized prefix traffic: the XLA gather pays three
    # passes per prefix token (cache read + materialized gather write +
    # its re-read), the paged-DMA kernel streams it once — weights and
    # the KV write are route-independent. Triangular prefix for
    # chunks=3, ctx=300 is 300 tokens, so the routes differ by exactly
    # two extra passes over it.
    kern = t.sp_prefill_read_bytes(3, 300, kernel=True)
    assert three - kern == pytest.approx(2 * 300 * t.kv_bytes_per_token)
    # one chunk has no committed prefix: the routes cost the same
    assert t.sp_prefill_read_bytes(1, 100, kernel=True) == pytest.approx(one)


# --------------------------------------------------------------------------
# SLO attainment + goodput
# --------------------------------------------------------------------------


def test_slo_verdicts_and_goodput_counters():
    clock = {"t": 0.0}
    slo = SloTracker(ttft_s=0.5, itl_s=0.1, clock=lambda: clock["t"])
    assert slo.observe(0.2, 0.05, tokens=10) is True     # both met
    assert slo.observe(0.9, 0.05, tokens=10) is False    # ttft miss
    assert slo.observe(0.2, 0.5, tokens=10) is False     # worst-gap miss
    assert slo.observe(0.2, None, tokens=1) is True      # single token
    text = slo.registry.render()
    assert 'dynamo_slo_attainment_total{met="true",slo="ttft"} 3.0' in text
    assert 'dynamo_slo_attainment_total{met="false",slo="ttft"} 1.0' in text
    assert 'dynamo_slo_attainment_total{met="false",slo="itl"} 1.0' in text
    # the per-request conjunction rides the same counter — the fleet
    # hub's attainment rollup consumes this (the dimension series blend
    # would overstate attainment when a dimension misses)
    assert 'dynamo_slo_attainment_total{met="true",slo="request"} 2.0' in text
    assert 'dynamo_slo_attainment_total{met="false",slo="request"} 2.0' in text
    assert "dynamo_slo_goodput_tokens_total 11.0" in text
    assert 'dynamo_slo_target_seconds{slo="ttft"} 0.5' in text
    snap = slo.snapshot()
    assert snap["slo.attainment"] == pytest.approx(0.5)
    assert snap["slo.ttft_attainment"] == pytest.approx(0.75)
    assert snap["slo.goodput_tokens_per_s"] > 0


def test_slo_snapshot_goes_blind_outside_window():
    clock = {"t": 0.0}
    slo = SloTracker(ttft_s=0.5, window_s=10.0, clock=lambda: clock["t"])
    slo.observe(0.1, None, tokens=5)
    clock["t"] = 60.0
    assert slo.snapshot() == {}  # the policy skips, never acts on stale


def test_slo_goodput_rate_survives_capacity_truncation():
    """Above ~68 completed req/s the verdict deque (maxlen 4096) evicts
    in-window rows; the goodput rate must divide by the span the
    RETAINED rows cover, not the full window — otherwise a sustained
    200 req/s reads 3x low into the planner."""
    clock = {"t": 0.0}
    slo = SloTracker(ttft_s=10.0, window_s=60.0, clock=lambda: clock["t"])
    rate, tokens = 200.0, 50
    # 90 s of sustained traffic: the deque retains only the newest
    # 4096 verdicts (~20.5 s of it)
    n = int(90 * rate)
    for i in range(n):
        clock["t"] = i / rate
        slo.observe(0.1, None, tokens=tokens)
    snap = slo.snapshot()
    true_rate = rate * tokens
    assert snap["slo.goodput_tokens_per_s"] == pytest.approx(
        true_rate, rel=0.05)
    # attainment fractions are ratios over the same rows — unaffected
    assert snap["slo.attainment"] == 1.0


def test_policy_sheds_on_slo_attainment_floor():
    """The control loop acts on user-visible latency: attainment below
    the floor reads as saturation and steps the shed ladder."""
    from dynamo_tpu.planner.policy import (
        SIG_SLO_ATTAINMENT,
        PolicyConfig,
        SlaPolicy,
    )
    from dynamo_tpu.planner.signals import SignalStore

    clock = {"t": 0.0}
    signals = SignalStore(clock=lambda: clock["t"])
    policy = SlaPolicy(PolicyConfig(slo_attainment_floor=0.9),
                       clock=lambda: clock["t"])
    signals.observe(SIG_SLO_ATTAINMENT, 0.4)
    actions = policy.decide(signals, {})
    shed = [a for a in actions if getattr(a, "shed_level", 0) == 1]
    assert shed and "slo attainment" in shed[0].reason
    # healthy attainment does NOT trip it
    policy2 = SlaPolicy(PolicyConfig(slo_attainment_floor=0.9),
                        clock=lambda: clock["t"])
    signals2 = SignalStore(clock=lambda: clock["t"])
    signals2.observe(SIG_SLO_ATTAINMENT, 0.99)
    assert not policy2.decide(signals2, {})


# --------------------------------------------------------------------------
# flightdump --trace: the offline X-ray
# --------------------------------------------------------------------------


def _run_flightdump(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts", "flightdump.py"),
         *args],
        capture_output=True, text=True,
    )


def test_flightdump_trace_from_artifact(tmp_path):
    artifact = {"version": 1, "reason": "test", "events": [],
                "traces": [_trace_with_remote()]}
    path = tmp_path / "flight.json"
    path.write_text(json.dumps(artifact))
    res = _run_flightdump(str(path), "--trace", "r1")
    assert res.returncode == 0, res.stderr
    assert "decode_engine" in res.stdout
    assert "prefill_worker" in res.stdout
    assert "CLOCK OFFSET" in res.stdout
    # the +1000 s skew was corrected, not rendered as a span position
    assert "+1000" not in res.stdout.split("CLOCK OFFSET")[0]


def test_flightdump_trace_from_jsonl_sink(tmp_path):
    path = tmp_path / "traces.jsonl"
    path.write_text(json.dumps(_trace_with_remote()) + "\n")
    res = _run_flightdump(str(path), "--trace", "r1")
    assert res.returncode == 0, res.stderr
    assert "prefill_worker" in res.stdout


def test_flightdump_trace_unknown_id_exits_2(tmp_path):
    path = tmp_path / "flight.json"
    path.write_text(json.dumps({"traces": [_trace_with_remote()]}))
    res = _run_flightdump(str(path), "--trace", "nope")
    assert res.returncode == 2
    assert "no trace" in res.stderr


# --------------------------------------------------------------------------
# cross-process hop over the runtime plane: spans ride the end frame
# --------------------------------------------------------------------------


@pytest.mark.asyncio
async def test_worker_spans_fold_into_requester_context():
    from dynamo_tpu.runtime.client import Client
    from dynamo_tpu.runtime.component import DistributedRuntime
    from dynamo_tpu.runtime.engine import AsyncEngineContext, Context
    from dynamo_tpu.runtime.transports.memory import MemoryHub

    drt = DistributedRuntime.in_process(MemoryHub())
    ep = drt.namespace("t").component("w").endpoint("gen")

    async def handler(payload, ctx):
        ctx.add_stage("admission")
        await asyncio.sleep(0.01)
        ctx.add_stage("completion")
        yield {"ok": True, "trace": ctx.trace_id}

    serving = await ep.serve(handler, span_source="decode_engine")
    client = await Client(ep).start()
    await client.wait_for_instances(1)
    ctx = Context({"x": 1}, AsyncEngineContext(trace_id="xray-hop"))
    items = [item async for item in client.generate(ctx)]
    assert items[0]["trace"] == "xray-hop"  # trace context crossed
    assert len(ctx.context.remote_spans) == 1
    rs = ctx.context.remote_spans[0]
    assert rs["source"] == "decode_engine"
    assert [n for n, _ in rs["spans"]] == ["admission", "completion"]
    # same process, same clock: the estimated offset is ~0 and the span
    # durations survive the fold (completion ≈ 10 ms after admission)
    assert abs(rs["offset_s"]) < 0.05
    assert rs["spans"][1][1] - rs["spans"][0][1] == pytest.approx(
        0.01, abs=0.05)
    await serving.stop()
    await client.close()
    await drt.close()


# --------------------------------------------------------------------------
# the X-ray e2e: frontend → decode engine → prefill worker, one timeline
# --------------------------------------------------------------------------


from test_jax_engine import hf_model_dir, TINY  # noqa: F401,E402


async def test_stitched_disagg_request_spans_three_processes(hf_model_dir):
    """A remote-prefilled request served over the runtime plane returns
    ONE stitched timeline containing frontend, decode-engine, and
    prefill-worker spans (incl. the transfer span) on a single
    clock-adjusted axis — and the stream stays byte-identical to pure
    local generation."""
    import jax.numpy as jnp

    from dynamo_tpu.disagg import (
        DisaggRouter,
        PrefillWorker,
        RemotePrefillCoordinator,
    )
    from dynamo_tpu.engine.config import EngineConfig, ModelConfig
    from dynamo_tpu.engine.model_runner import ModelRunner
    from dynamo_tpu.engine.scheduler import EngineRequest, Scheduler
    from dynamo_tpu.models.loader import load_llama_params
    from dynamo_tpu.protocols.common import (
        EngineOutput,
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.client import Client
    from dynamo_tpu.runtime.component import DistributedRuntime
    from dynamo_tpu.runtime.engine import AsyncEngineContext, Context
    from dynamo_tpu.runtime.transports.memory import MemoryHub

    def make_runner():
        cfg = ModelConfig.from_model_dir(hf_model_dir)
        econfig = EngineConfig(
            model=cfg, max_batch_size=4, max_model_len=128, kv_block_size=8,
            num_kv_blocks=64, dtype="float32",
        )
        params = load_llama_params(hf_model_dir, cfg, jnp.float32)
        return ModelRunner(econfig, params=params), econfig

    prompt = [1, 17, 43, 99, 7, 3, 250, 12, 5, 77, 8, 21]
    req = PreprocessedRequest(
        token_ids=list(prompt),
        sampling_options=SamplingOptions(temperature=0.0),
        stop_conditions=StopConditions(max_tokens=8, ignore_eos=True),
    )

    # baseline: local-only engine
    runner_l, econfig = make_runner()
    sched_l = Scheduler(runner_l, econfig)
    sched_l.start()
    er = EngineRequest(request_id="base", prompt=list(prompt), req=req,
                       ctx=Context(req).context, out_queue=asyncio.Queue())
    sched_l.add_request(er)
    baseline = []
    while True:
        out = await asyncio.wait_for(er.out_queue.get(), timeout=60)
        if out is None:
            break
        baseline.extend(out.token_ids)
    await sched_l.stop()
    assert len(baseline) == 8

    # decode "process": scheduler + disagg coordinator behind an endpoint
    hub = MemoryHub()
    drt_w = DistributedRuntime.in_process(hub)
    runner_d, dconfig = make_runner()
    coord = RemotePrefillCoordinator(
        drt_w, runner_d,
        router=DisaggRouter(max_local_prefill_length=0,
                            max_prefill_queue_size=100),
        depth_refresh_s=0.05,
    )
    await coord.start()
    sched = Scheduler(runner_d, dconfig, disagg=coord)
    sched.start()
    ep = drt_w.namespace("public").component("backend").endpoint("generate")

    async def handler(payload, ctx):
        r = PreprocessedRequest.from_wire(payload)
        e = EngineRequest(request_id=ctx.id, prompt=list(r.token_ids),
                          req=r, ctx=ctx, out_queue=asyncio.Queue())
        sched.add_request(e)
        while True:
            out = await e.out_queue.get()
            if out is None:
                return
            yield out.to_wire()

    serving = await ep.serve(handler, span_source="decode_engine")

    # prefill "process"
    drt_p = DistributedRuntime.in_process(hub)
    runner_p, pconfig = make_runner()
    worker = PrefillWorker(drt_p, runner_p, pconfig)
    worker_task = asyncio.create_task(worker.run())

    # frontend "process"
    drt_f = DistributedRuntime.in_process(hub)
    client = await Client(
        drt_f.namespace("public").component("backend").endpoint("generate")
    ).start()
    await client.wait_for_instances(1)
    try:
        fctx = Context(req.to_wire(),
                       AsyncEngineContext(trace_id="xray-e2e"))
        fctx.add_stage("http")
        toks = []
        async for item in client.generate(fctx):
            toks.extend(EngineOutput.from_wire(item).token_ids)
        assert toks == baseline  # streams unchanged, byte-identical
        assert coord.remote_completed == 1

        # the decode worker's spans (and, nested, the prefill worker's)
        # folded into the frontend context off the end frame
        rec = TraceRecorder(capacity=8, ttl_s=0)
        trace = rec.record("xray-e2e", "tiny", "success", fctx.stages,
                           ctx=fctx.context)
        stitched = stitched_timeline(trace)
        sources = {s["source"] for s in stitched["sources"]}
        assert {"frontend", "decode_engine", "prefill_worker"} <= sources
        names = {(r["source"], r["name"]) for r in stitched["timeline"]}
        # the decode engine's side of the hop, incl. the transfer span
        assert ("decode_engine", "admission") in names
        assert ("decode_engine", "kv_transfer") in names
        assert ("decode_engine", "first_token") in names
        assert ("decode_engine", "completion") in names
        # the prefill worker's side: dequeue → compute → transfer
        assert ("prefill_worker", "prefill.compute") in names
        assert ("prefill_worker", "prefill.transfer") in names
        # one consistent axis: in-process clocks agree, so every span
        # must land inside the request's own wall window (generous slop
        # for the offset estimators' queue-transit error)
        total = trace["total_s"]
        for row in stitched["timeline"]:
            assert -0.5 <= row["start_s"] <= total + 0.5, row
        # chronology across sources: remote prefill compute completes
        # before the decode engine's remote_prefill install mark
        pc = [r for r in stitched["timeline"]
              if (r["source"], r["name"]) == ("prefill_worker",
                                              "prefill.compute")][0]
        ft = [r for r in stitched["timeline"]
              if (r["source"], r["name"]) == ("decode_engine",
                                              "completion")][0]
        assert pc["start_s"] < ft["start_s"] + ft["duration_s"]
    finally:
        worker_task.cancel()
        await worker.close()
        await client.close()
        await serving.stop()
        await sched.stop()
        await drt_f.close()
        await drt_p.close()
        await drt_w.close()
