"""Pallas kernel compile probes + hang-proof warmup (ops/probe.py).

The failure mode under test is the one that killed round 2's telemetry:
a Mosaic kernel compile that HANGS (not fails) wedges the host's shared
compile service for every process. The engine must therefore never start
a first Pallas compile in-process — ops/probe.py runs it in a child with
a hard timeout, and ModelRunner.warmup consults the probe before any
in-process compile under ``attention_impl: auto``.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.model_runner import ModelRunner
from dynamo_tpu.ops import attention as attn_mod
from dynamo_tpu.ops import probe as probe_mod


@pytest.fixture(autouse=True)
def _clear_probe_cache(monkeypatch):
    probe_mod._PROBE_CACHE.clear()
    # tests below control probe behavior explicitly
    monkeypatch.delenv("DYN_SKIP_PALLAS_PROBE", raising=False)
    monkeypatch.delenv("DYN_FORCE_XLA", raising=False)
    yield
    probe_mod._PROBE_CACHE.clear()


def tiny_runner(attention_impl="auto"):
    cfg = ModelConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=8, attention_impl=attention_impl,
    )
    econfig = EngineConfig(
        model=cfg, max_batch_size=2, max_model_len=32, kv_block_size=8,
        num_kv_blocks=16, dtype="float32", prefill_buckets=[16],
        allow_random_weights=True,
    )
    return ModelRunner(econfig), econfig


def test_probe_times_out_on_hanging_compile(monkeypatch):
    """A probe child that never finishes (the Mosaic-hang stand-in) must
    come back False within the timeout, not block forever."""
    monkeypatch.setattr(
        probe_mod, "_PROBE_SRC", "import time\ntime.sleep(600)\n"
    )
    t0 = time.monotonic()
    assert probe_mod.probe_kernel("decode", timeout_s=2.0) is False
    assert time.monotonic() - t0 < 30
    # memoized: the second call must not pay the timeout again
    t1 = time.monotonic()
    assert probe_mod.probe_kernel("decode", timeout_s=2.0) is False
    assert time.monotonic() - t1 < 0.1


def test_probe_fails_cleanly_on_cpu():
    """pallas_call is uncompilable on the CPU backend without interpret
    mode — a real failed-compile probe, exercised end-to-end."""
    assert probe_mod.probe_kernel("decode", timeout_s=120.0) is False


def test_probe_env_overrides(monkeypatch):
    monkeypatch.setenv("DYN_FORCE_XLA", "1")
    assert probe_mod.probe_kernel("decode") is False
    monkeypatch.delenv("DYN_FORCE_XLA")
    monkeypatch.setenv("DYN_SKIP_PALLAS_PROBE", "1")
    assert probe_mod.probe_kernel("decode") is True


def test_probe_multi_kind_partial_credit(monkeypatch):
    """One child probes all kinds; kinds that printed PROBE_OK before the
    child died are credited, the rest are not."""
    monkeypatch.setattr(
        probe_mod, "_PROBE_SRC",
        "print('PROBE_OK decode', flush=True)\nraise SystemExit(1)\n",
    )
    res = probe_mod.probe_kernels(["decode", "prefill"], timeout_s=60)
    assert res == {"decode": True, "prefill": False}


def test_probe_exclusive_device_is_inconclusive(monkeypatch):
    """A child that cannot acquire the TPU (this process holds it) must
    not condemn the kernels — warmup then compiles in-process as before."""
    monkeypatch.setattr(
        probe_mod, "_PROBE_SRC",
        "import sys\n"
        "sys.stderr.write('The TPU is already in use by process 123\\n')\n"
        "raise SystemExit(1)\n",
    )
    res = probe_mod.probe_kernels(["decode", "prefill"], timeout_s=60)
    assert res == {"decode": None, "prefill": None}
    # serving treats inconclusive as "try in-process" (True)
    probe_mod._PROBE_CACHE.clear()
    assert probe_mod.probe_serving_kernels() is True


def test_serving_probe_kinds():
    """MLA engines compile ONLY the MLA decode kernel on the pallas path
    (deepseek.py) — the probe must not gate them on the dense kernels;
    dense engines probe decode + flash prefill."""
    seen = []

    def fake(kinds, timeout_s=0.0, cwd=None):
        seen.append(list(kinds))
        return {k: True for k in kinds}

    orig = probe_mod.probe_kernels
    probe_mod.probe_kernels = fake
    try:
        assert probe_mod.probe_serving_kernels(mla=True) is True
        assert probe_mod.probe_serving_kernels(mla=False) is True
    finally:
        probe_mod.probe_kernels = orig
    assert seen == [["mla_decode"], ["decode", "prefill"]]


def test_warmup_consults_probe_before_any_pallas_compile(monkeypatch):
    """auto + failing probe → warmup flips to XLA without ever building
    a Pallas program in-process (a hanging compile would thus never run
    in the serving process)."""
    calls = []
    monkeypatch.setattr(
        attn_mod, "resolve_attention_impl",
        lambda impl: "pallas" if impl == "auto" else impl,
    )
    monkeypatch.setattr(
        probe_mod, "probe_serving_kernels",
        lambda mla=False, timeout_s=0, **kw: calls.append((mla, timeout_s)) or False,
    )
    runner, econfig = tiny_runner("auto")
    runner.warmup()
    assert calls, "warmup did not consult the probe"
    assert econfig.model.attention_impl == "xla"
    out, *_ = runner.step(
        np.zeros((2, 1), np.int32), np.zeros((2, 1), np.int32),
        np.zeros((2, 4), np.int32), np.full((2, 1), -1, np.int32),
        np.ones(2, np.int32), np.zeros(2, np.int32),
        np.zeros(2, np.float32), np.zeros(2, np.int32),
        np.ones(2, np.float32), jax.random.PRNGKey(0),
    )
    assert np.asarray(out).shape == (2,)


def test_warmup_inprocess_failure_reinits_donated_state(monkeypatch):
    """Probe passes (tiny shapes) but the full-shape in-process compile
    fails → fallback must re-initialize the donated cache/sample-state
    buffers before retrying, then serve on XLA."""
    monkeypatch.setattr(
        attn_mod, "resolve_attention_impl",
        lambda impl: "pallas" if impl == "auto" else impl,
    )
    monkeypatch.setattr(
        probe_mod, "probe_serving_kernels", lambda mla=False, timeout_s=0, **kw: True
    )
    runner, econfig = tiny_runner("auto")
    runner.warmup()  # pallas fails on CPU → except-path fallback
    assert econfig.model.attention_impl == "xla"
    for arr in (*runner.kv_cache, *runner.sample_state):
        assert not arr.is_deleted()
    out, *_ = runner.step(
        np.zeros((2, 1), np.int32), np.zeros((2, 1), np.int32),
        np.zeros((2, 4), np.int32), np.full((2, 1), -1, np.int32),
        np.ones(2, np.int32), np.zeros(2, np.int32),
        np.zeros(2, np.float32), np.zeros(2, np.int32),
        np.ones(2, np.float32), jax.random.PRNGKey(0),
    )
    assert np.asarray(out).shape == (2,)


def test_mla_models_probe_mla_kernel(monkeypatch):
    seen = {}
    monkeypatch.setattr(
        attn_mod, "resolve_attention_impl",
        lambda impl: "pallas" if impl == "auto" else impl,
    )
    monkeypatch.setattr(
        probe_mod, "probe_serving_kernels",
        lambda mla=False, timeout_s=0, **kw: seen.setdefault("mla", mla) or False,
    )
    cfg = ModelConfig(
        vocab_size=128, hidden_size=32, intermediate_size=48, num_layers=2,
        num_heads=4, num_kv_heads=4, head_dim=16, kv_lora_rank=16,
        qk_rope_head_dim=8, qk_nope_head_dim=12, v_head_dim=12,
        attention_impl="auto",
    )
    econfig = EngineConfig(
        model=cfg, max_batch_size=2, max_model_len=32, kv_block_size=8,
        num_kv_blocks=16, dtype="float32", prefill_buckets=[16],
        allow_random_weights=True,
    )
    runner = ModelRunner(econfig)
    runner.warmup()
    assert seen["mla"] is True
    assert cfg.attention_impl == "xla"


def test_probe_matrix_matches_engine_compilations(monkeypatch):
    """probe_serving_kernels must request EXACTLY the kernel
    specializations the engine's config will compile — the static keys
    are (softcap on/off, sinks on/off, cache dtype). The sliding window
    is a runtime operand, never a specialization: a window-only model
    (Mistral/Phi-3) compiles the base pair, a softcap model (Gemma-2)
    ONLY the softcap pair — one pair per config, never both."""
    captured = {}

    def fake_probe_kernels(kinds, timeout_s=0.0, cwd=None):
        captured["kinds"] = list(kinds)
        return {k: True for k in kinds}

    monkeypatch.setattr(probe_mod, "probe_kernels", fake_probe_kernels)

    cases = [
        (dict(), ["decode", "prefill"]),
        (dict(softcap=True),  # "windowed" kinds ARE the softcap pair
         ["decode_windowed", "prefill_windowed"]),
        (dict(fp8_kv=True), ["decode_fp8", "prefill_fp8"]),
        (dict(softcap=True, fp8_kv=True),
         ["decode_windowed_fp8", "prefill_windowed_fp8"]),
        (dict(sinks=True), ["decode_sinks", "prefill_sinks"]),
        (dict(sinks=True, fp8_kv=True),
         ["decode_sinks_fp8", "prefill_sinks_fp8"]),
        (dict(sinks=True, softcap=True),  # gptoss: window rides the
         ["decode_sinks", "prefill_sinks"]),  # sinks specialization
        (dict(mla=True), ["mla_decode"]),
        (dict(mla=True, fp8_kv=True), ["mla_decode_fp8"]),
        # the verify kernel's softcap / sinks / fp8-KV specializations:
        # a speculative engine probes EXACTLY the variant its model
        # config serves with, never the base kind plus a variant
        (dict(verify=True), ["decode", "prefill", "verify"]),
        (dict(verify=True, fp8_kv=True),
         ["decode_fp8", "prefill_fp8", "verify_fp8"]),
        (dict(verify=True, softcap=True),
         ["decode_windowed", "prefill_windowed", "verify_softcap"]),
        (dict(verify=True, softcap=True, fp8_kv=True),
         ["decode_windowed_fp8", "prefill_windowed_fp8",
          "verify_softcap_fp8"]),
        (dict(verify=True, sinks=True),
         ["decode_sinks", "prefill_sinks", "verify_sinks"]),
        (dict(verify=True, sinks=True, fp8_kv=True),
         ["decode_sinks_fp8", "prefill_sinks_fp8", "verify_sinks_fp8"]),
        # the SP ring-prefill page-walk kernel and the fused sampling
        # epilogue ride the same warmup probe pass as the attention
        # kernels — engaged exactly when the engine config compiles them
        (dict(sp_prefill=True), ["decode", "prefill", "sp_prefill"]),
        (dict(epilogue=True), ["decode", "prefill", "epilogue"]),
        (dict(mla=True, epilogue=True), ["mla_decode", "epilogue"]),
        (dict(verify=True, sp_prefill=True, epilogue=True),
         ["decode", "prefill", "verify", "sp_prefill", "epilogue"]),
    ]
    for kwargs, want in cases:
        assert probe_mod.probe_serving_kernels(**kwargs), kwargs
        assert captured["kinds"] == want, (kwargs, captured["kinds"])
        # every requested kind must exist in the child's probe registry
        # (PROBES lives inside the subprocess source string)
        for k in want:
            assert f'"{k}"' in probe_mod._PROBE_SRC, k
