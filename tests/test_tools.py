"""Tool-call parsing → OpenAI tool_calls (llm/tools.py + chat_stream).

Reference analog: lib/llm/src/preprocessor/tools.rs ToolCallingMatcher
(whole-message JSON); this framework also parses hermes/mistral formats
and actually wires the result into the delta stream + aggregator, which
the reference leaves as a TODO (chat_completions/delta.rs:131)."""

import asyncio
import json

import pytest

from dynamo_tpu.llm.tools import parse_tool_calls
from dynamo_tpu.protocols.common import BackendOutput, FinishReason
from dynamo_tpu.protocols.openai import aggregate_chat_stream


def _args(call):
    return json.loads(call["function"]["arguments"])


def test_parse_whole_json_object():
    calls = parse_tool_calls('{"name": "get_weather", "arguments": {"city": "SF"}}')
    assert len(calls) == 1
    assert calls[0]["type"] == "function"
    assert calls[0]["function"]["name"] == "get_weather"
    assert _args(calls[0]) == {"city": "SF"}
    assert calls[0]["id"].startswith("call-")


def test_parse_json_parameters_key_and_array():
    calls = parse_tool_calls(
        '[{"name": "a", "parameters": {"x": 1}}, {"name": "b", "arguments": {}}]'
    )
    assert [c["function"]["name"] for c in calls] == ["a", "b"]
    assert _args(calls[0]) == {"x": 1}


def test_parse_hermes_blocks():
    text = (
        'I will check.\n<tool_call>\n{"name": "lookup", "arguments": {"q": "tpu"}}\n'
        '</tool_call><tool_call>{"name": "sum", "arguments": {"a": 1, "b": 2}}</tool_call>'
    )
    calls = parse_tool_calls(text)
    assert [c["function"]["name"] for c in calls] == ["lookup", "sum"]
    assert _args(calls[1]) == {"a": 1, "b": 2}


def test_parse_mistral_prefix():
    calls = parse_tool_calls('[TOOL_CALLS] [{"name": "f", "arguments": {"k": "v"}}]')
    assert calls[0]["function"]["name"] == "f"


def test_plain_text_is_not_a_tool_call():
    assert parse_tool_calls("The weather in SF is sunny.") is None
    assert parse_tool_calls('{"no_name_key": 1}') is None
    assert parse_tool_calls("<tool_call>not json</tool_call>") is None


def test_explicit_format_rejects_others():
    assert parse_tool_calls('{"name": "f", "arguments": {}}', fmt="hermes") is None
    with pytest.raises(ValueError):
        parse_tool_calls("x", fmt="nope")


# ---------- chat_stream integration ----------


async def _fake_backend(texts, finish=FinishReason.STOP):
    async def gen():
        for i, t in enumerate(texts):
            yield BackendOutput(
                text=t,
                token_ids=[i],
                cum_tokens=i + 1,
                finish_reason=finish if i == len(texts) - 1 else None,
            )
    return gen()


def _mk_preprocessor():
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor

    mdc = ModelDeploymentCard(display_name="t", slug="t", model_path=None)

    class _NullTok:
        def id_to_token(self, i):
            return str(i)

    return OpenAIPreprocessor(mdc, tokenizer=_NullTok())


@pytest.mark.asyncio
async def test_chat_stream_emits_tool_call_delta():
    pre = _mk_preprocessor()
    stream = await _fake_backend(
        ['{"name": "get_w', 'eather", "arguments": {"city": "SF"}}']
    )
    chunks = [
        c async for c in pre.chat_stream(
            "id1", "m", stream, prompt_tokens=3, tool_format="auto"
        )
    ]
    # role chunk + streamed tool-call deltas; raw JSON is never streamed
    # as content
    assert all(not c.choices or not c.choices[0].delta.content for c in chunks)
    final = chunks[-1]
    assert final.choices[0].finish_reason == "tool_calls"
    assert not final.choices[0].delta.tool_calls  # closing chunk is empty
    # the OpenAI streamed shape: a header delta (index/id/type/name, empty
    # arguments) followed by argument-fragment deltas carrying only
    # {index, function.arguments}
    tc_chunks = [
        c.choices[0].delta.tool_calls[0] for c in chunks
        if c.choices and c.choices[0].delta.tool_calls
    ]
    header, frag = tc_chunks[0], tc_chunks[1]
    assert header["index"] == 0
    assert header["id"].startswith("call-")
    assert header["type"] == "function"
    assert header["function"] == {"name": "get_weather", "arguments": ""}
    assert frag["index"] == 0 and "id" not in frag
    assert json.loads(frag["function"]["arguments"]) == {"city": "SF"}

    resp = aggregate_chat_stream(chunks)
    assert resp.choices[0].finish_reason == "tool_calls"
    call = resp.choices[0].message.tool_calls[0]
    assert call["function"]["name"] == "get_weather"
    assert json.loads(call["function"]["arguments"]) == {"city": "SF"}
    assert call["id"].startswith("call-")
    assert "index" not in call


@pytest.mark.asyncio
async def test_chat_stream_streams_prose_incrementally_with_tools():
    """tools enabled + plain prose answer → content streams as it is
    generated (no call marker ever appears), not as one final flush."""
    pre = _mk_preprocessor()
    stream = await _fake_backend(["It is ", "sunny."])
    chunks = [
        c async for c in pre.chat_stream(
            "id2", "m", stream, prompt_tokens=3, tool_format="auto"
        )
    ]
    texts = [
        c.choices[0].delta.content for c in chunks
        if c.choices and c.choices[0].delta.content
    ]
    assert len(texts) >= 2  # incremental, not one buffered flush
    resp = aggregate_chat_stream(chunks)
    assert resp.choices[0].message.content == "It is sunny."
    assert resp.choices[0].finish_reason == "stop"
    assert resp.choices[0].message.tool_calls is None


@pytest.mark.asyncio
async def test_chat_stream_jails_marker_split_across_chunks():
    """Prose streams; a <tool_call> marker arriving SPLIT across deltas is
    still withheld and parsed (the marker-prefix jail)."""
    pre = _mk_preprocessor()
    stream = await _fake_backend([
        "Let me check. ", "<tool_",
        'call>{"name": "get_weather", "arguments": {"city": "SF"}}</tool_call>',
    ])
    chunks = [
        c async for c in pre.chat_stream(
            "id3", "m", stream, prompt_tokens=3, tool_format="hermes"
        )
    ]
    texts = [
        c.choices[0].delta.content for c in chunks
        if c.choices and c.choices[0].delta.content
    ]
    # the prose streamed, the raw call syntax never did
    assert any("Let me check." in t for t in texts)
    assert not any("<tool_call>" in t for t in texts)
    final = chunks[-1]
    assert final.choices[0].finish_reason == "tool_calls"
    headers = [
        c.choices[0].delta.tool_calls[0] for c in chunks
        if c.choices and c.choices[0].delta.tool_calls
        and "id" in c.choices[0].delta.tool_calls[0]
    ]
    assert headers[0]["function"]["name"] == "get_weather"


@pytest.mark.asyncio
async def test_chat_stream_without_tools_streams_normally():
    pre = _mk_preprocessor()
    stream = await _fake_backend(["a", "b"])
    chunks = [
        c async for c in pre.chat_stream("id3", "m", stream, prompt_tokens=1)
    ]
    texts = [c.choices[0].delta.content for c in chunks if c.choices and c.choices[0].delta.content]
    assert texts == ["a", "b"]


def test_extract_preserves_surrounding_content():
    from dynamo_tpu.llm.tools import extract_tool_calls

    content, calls = extract_tool_calls(
        'Let me check.\n<tool_call>{"name": "f", "arguments": {}}</tool_call>'
    )
    assert content == "Let me check."
    assert calls[0]["function"]["name"] == "f"
    content, calls = extract_tool_calls("plain text")
    assert content == "plain text" and calls is None


def test_bad_tool_format_rejected_at_construction():
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
    from dynamo_tpu.runtime.engine import EngineError

    mdc = ModelDeploymentCard(
        display_name="t", slug="t", tool_call_format="llama9"
    )
    with pytest.raises(EngineError, match="tool_call_format"):
        OpenAIPreprocessor(mdc, tokenizer=object())


@pytest.mark.asyncio
async def test_n_fan_out_yields_indexed_choices():
    """n=2 runs two engine streams; choices carry distinct indices and the
    aggregate has two choices (reference SamplingOptions.n parity)."""
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
    from dynamo_tpu.protocols.common import (
        BackendOutput,
        FinishReason,
        PreprocessedRequest,
    )
    from dynamo_tpu.protocols.openai import ChatCompletionRequest
    from dynamo_tpu.runtime.engine import AsyncEngine, Context

    calls = []

    class _Engine(AsyncEngine):
        async def generate(self, request):
            req = request.payload
            calls.append(req.sampling_options.seed)
            text = f"answer-{len(calls)}"
            yield BackendOutput(
                text=text, token_ids=[1], cum_tokens=1,
                finish_reason=FinishReason.STOP,
            )

    class _Tok:
        def encode(self, text, add_special_tokens=False):
            return [1, 2, 3]

        def id_to_token(self, i):
            return str(i)

    mdc = ModelDeploymentCard(display_name="t", slug="t")
    pre = OpenAIPreprocessor(mdc, tokenizer=_Tok())
    req = ChatCompletionRequest(
        model="m", messages=[{"role": "user", "content": "hi"}],
        n=2, seed=100,
        stream_options={"include_usage": True}, stream=True,
    )
    chunks = [c async for c in pre.generate(Context(req), _Engine())]

    # per-choice seeds are isolated and derived from the request seed
    assert sorted(calls) == [100, 101]
    indices = {
        ch.index
        for c in chunks for ch in c.choices
        if ch.delta.content
    }
    assert indices == {0, 1}
    usage = [c.usage for c in chunks if c.usage is not None]
    assert len(usage) == 1 and usage[0].completion_tokens == 2

    from dynamo_tpu.protocols.openai import aggregate_chat_stream

    resp = aggregate_chat_stream(chunks)
    assert len(resp.choices) == 2
    contents = {c.message.content for c in resp.choices}
    assert contents == {"answer-1", "answer-2"}


@pytest.mark.asyncio
async def test_n_fan_out_choices_do_not_truncate_each_other():
    """Engines stop their request context when a stream completes (the
    serving engine does this in its finally); with n>1 each choice must
    own its context or the first finisher truncates the siblings."""
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
    from dynamo_tpu.protocols.common import BackendOutput, FinishReason
    from dynamo_tpu.protocols.openai import ChatCompletionRequest, aggregate_chat_stream
    from dynamo_tpu.runtime.engine import AsyncEngine, Context

    started = []

    class _Engine(AsyncEngine):
        async def generate(self, request):
            i = len(started)
            started.append(i)
            try:
                steps = 1 if i == 0 else 4  # choice 0 finishes first
                for k in range(steps):
                    if request.context.is_stopped and k > 0:
                        return  # honor cooperative cancellation
                    await asyncio.sleep(0.01)
                    yield BackendOutput(
                        text=f"c{i}t{k} ", token_ids=[k], cum_tokens=k + 1,
                        finish_reason=FinishReason.STOP if k == steps - 1 else None,
                    )
            finally:
                # the serving engine stops the context when ITS stream ends
                request.context.stop_generating()

    class _Tok:
        def encode(self, text, add_special_tokens=False):
            return [1]

        def id_to_token(self, i):
            return str(i)

    pre = OpenAIPreprocessor(
        ModelDeploymentCard(display_name="t", slug="t"), tokenizer=_Tok()
    )
    req = ChatCompletionRequest(
        model="m", messages=[{"role": "user", "content": "x"}], n=2,
    )
    chunks = [c async for c in pre.generate(Context(req), _Engine())]
    resp = aggregate_chat_stream(chunks)
    by_index = {c.index: c.message.content for c in resp.choices}
    assert by_index[0] == "c0t0 "
    assert by_index[1] == "c1t0 c1t1 c1t2 c1t3 ", by_index


@pytest.mark.asyncio
async def test_jail_splits_logprob_entries_at_marker_boundary():
    """ADVICE r2: prose released before a mid-chunk marker must stream
    WITH its own logprob entries; only the withheld tokens' entries ride
    the final tool-call chunk — no duplication, no misalignment."""
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
    from dynamo_tpu.protocols.common import TokenLogprob

    call = '{"name": "f", "arguments": {}}'
    vocab = {1: "Hi", 2: "<tool_call>", 3: call, 4: "</tool_call>"}

    class _MapTok:
        def id_to_token(self, i):
            return vocab.get(i, str(i))

    mdc = ModelDeploymentCard(display_name="t", slug="t", model_path=None)
    pre = OpenAIPreprocessor(mdc, tokenizer=_MapTok())

    async def gen():
        # one chunk carrying prose + the whole call — the marker lands
        # mid-chunk, exactly the case that used to strip the released
        # prose of its logprobs and duplicate them on the final chunk
        yield BackendOutput(
            text="Hi<tool_call>" + call + "</tool_call>",
            token_ids=[1, 2, 3, 4],
            cum_tokens=4,
            finish_reason=FinishReason.STOP,
            logprobs=[TokenLogprob(i, -0.25 * i) for i in (1, 2, 3, 4)],
        )

    chunks = [
        c async for c in pre.chat_stream(
            "id9", "m", gen(), prompt_tokens=1, tool_format="hermes"
        )
    ]
    prose = [
        c for c in chunks
        if c.choices and c.choices[0].delta.content == "Hi"
    ]
    assert len(prose) == 1
    (entries,) = [prose[0].choices[0].logprobs.content]
    assert [e.token for e in entries] == ["Hi"]
    assert any(
        c.choices and c.choices[0].delta.tool_calls for c in chunks
    )
    # the withheld tokens' entries ride the closing tool_calls chunk
    final = chunks[-1]
    assert final.choices[0].finish_reason == "tool_calls"
    held = final.choices[0].logprobs.content
    assert [e.token for e in held] == ["<tool_call>", call, "</tool_call>"]


# ---------- forced tool_choice (the delta.rs:131 leftover) ----------


def _chat_req(**kw):
    from dynamo_tpu.protocols.openai import ChatCompletionRequest

    return ChatCompletionRequest(
        model="m", messages=[{"role": "user", "content": "x"}], **kw
    )


def test_tool_choice_validation_rejects_bad_forms():
    from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
    from dynamo_tpu.runtime.engine import EngineError

    tools = [{"type": "function", "function": {"name": "f"}}]
    validate = OpenAIPreprocessor._validate_tool_choice

    # valid forms pass
    validate(_chat_req(tools=tools))
    validate(_chat_req(tools=tools, tool_choice="auto"))
    validate(_chat_req(tools=tools, tool_choice="none"))
    validate(_chat_req(tools=tools, tool_choice="required"))
    validate(_chat_req(tools=tools, tool_choice={
        "type": "function", "function": {"name": "f"}}))

    # a named function must exist in tools — 400 at the door, not a
    # wasted generation
    with pytest.raises(EngineError, match="not in tools"):
        validate(_chat_req(tools=tools, tool_choice={
            "type": "function", "function": {"name": "g"}}))
    with pytest.raises(EngineError, match="name is required"):
        validate(_chat_req(tools=tools, tool_choice={
            "type": "function", "function": {}}))
    with pytest.raises(EngineError, match="must be"):
        validate(_chat_req(tools=tools, tool_choice={"type": "tool"}))
    with pytest.raises(EngineError, match="unsupported tool_choice"):
        validate(_chat_req(tools=tools, tool_choice="sometimes"))
    with pytest.raises(EngineError, match="needs tools"):
        validate(_chat_req(tool_choice="required"))


@pytest.mark.asyncio
async def test_tool_jail_withholds_from_token_zero():
    """Forced tool_choice (required / named) jails from token 0: nothing
    streams while the call is being generated — the disobedient-prose
    case flushes once at the end as a single content chunk instead of
    streaming incrementally."""
    pre = _mk_preprocessor()
    # json format + prose that would NOT trigger the leading-{ jail:
    # without tool_jail this streams as two incremental content chunks
    stream = await _fake_backend(["Hello ", "world"])
    chunks = [
        c async for c in pre.chat_stream(
            "idj", "m", stream, prompt_tokens=1, tool_format="json",
            tool_jail=True,
        )
    ]
    content = [
        c.choices[0].delta.content for c in chunks
        if c.choices and c.choices[0].delta.content
    ]
    assert content == ["Hello world"]  # one flush, not incremental
    assert chunks[-1].choices[0].finish_reason == "stop"

    # same feed WITHOUT the jail: prose streams as it is generated
    stream2 = await _fake_backend(["Hello ", "world"])
    chunks2 = [
        c async for c in pre.chat_stream(
            "idk", "m", stream2, prompt_tokens=1, tool_format="json",
        )
    ]
    content2 = [
        c.choices[0].delta.content for c in chunks2
        if c.choices and c.choices[0].delta.content
    ]
    assert content2 == ["Hello ", "world"]


@pytest.mark.asyncio
async def test_tool_jail_parses_forced_call():
    pre = _mk_preprocessor()
    stream = await _fake_backend(
        ['{"name": "f", "argum', 'ents": {"k": 1}}']
    )
    chunks = [
        c async for c in pre.chat_stream(
            "idf", "m", stream, prompt_tokens=1, tool_format="json",
            tool_jail=True,
        )
    ]
    final = chunks[-1]
    assert final.choices[0].finish_reason == "tool_calls"
    resp = aggregate_chat_stream(chunks)
    call = resp.choices[0].message.tool_calls[0]
    assert call["function"]["name"] == "f"
    assert json.loads(call["function"]["arguments"]) == {"k": 1}


@pytest.mark.asyncio
async def test_generate_plumbs_tool_jail_for_forced_choice():
    """tool_choice='required' / named → generate() passes tool_jail to
    chat_stream (observed through the single-flush behavior above)."""
    from unittest import mock

    pre = _mk_preprocessor()
    req = _chat_req(
        tools=[{"type": "function", "function": {"name": "f"}}],
        tool_choice="required", stream=True,
    )
    pre.mdc.tool_call_format = "json"
    seen = {}

    async def fake_stream(*a, **kw):
        seen.update(kw)
        return
        yield  # pragma: no cover

    with mock.patch.object(pre, "preprocess_chat") as pc, \
            mock.patch.object(pre, "chat_stream", side_effect=fake_stream):
        from dynamo_tpu.protocols.common import (
            OutputOptions,
            PreprocessedRequest,
            SamplingOptions,
            StopConditions,
        )

        pc.return_value = PreprocessedRequest(
            token_ids=[1], stop_conditions=StopConditions(max_tokens=4),
            sampling_options=SamplingOptions(),
            output_options=OutputOptions(), model="m",
        )

        class _Next:
            def generate(self, ctx):
                async def g():
                    return
                    yield  # pragma: no cover
                return g()

        from dynamo_tpu.runtime.engine import Context

        [c async for c in pre.generate(Context(req), _Next())]
    assert seen.get("tool_format") == "json"
    assert seen.get("tool_jail") is True
