"""HTTP-edge admission control: unit contracts + the traffic-spike e2e.

The acceptance e2e (ISSUE 6): a fake engine behind the real HTTP
frontend, scripted spike of mixed-priority traffic → only the lowest
class sheds (429 + Retry-After), queued high-priority streams complete
byte-identically under the queue-wait deadline, and the planner's
scale-up lands as a replica patch observable in InMemoryKube.
"""

import asyncio

import aiohttp
import pytest

from dynamo_tpu.deploy import InMemoryKube, Reconciler
from dynamo_tpu.http.service import HttpService, ModelManager
from dynamo_tpu.planner import (
    AdmissionConfig,
    AdmissionController,
    AdmissionRejected,
    KubeActuator,
    LocalActuator,
    Planner,
    PolicyConfig,
    ScaleAction,
    SlaPolicy,
    parse_priority,
)
from dynamo_tpu.telemetry.flight import FlightRecorder


# --------------------------------------------------------------------------
# priority parsing
# --------------------------------------------------------------------------


def test_parse_priority_names_numbers_and_garbage():
    assert parse_priority("high") == 2
    assert parse_priority("HIGH ") == 2
    assert parse_priority("normal") == 1
    assert parse_priority("low") == 0
    assert parse_priority("2") == 2
    assert parse_priority("0") == 0
    # absent/garbage/out-of-range degrade to normal — never to highest
    assert parse_priority(None) == 1
    assert parse_priority("") == 1
    assert parse_priority("urgent!!") == 1
    assert parse_priority("99") == 1
    assert parse_priority("-1") == 1


# --------------------------------------------------------------------------
# controller unit contracts
# --------------------------------------------------------------------------


@pytest.mark.asyncio
async def test_slots_grant_highest_priority_first():
    ac = AdmissionController(
        AdmissionConfig(limit=1, queue_depth=8, queue_timeout_s=5.0),
        flight=FlightRecorder(16))
    await ac.acquire(1)  # takes the only slot
    order = []

    async def queued(priority, tag):
        await ac.acquire(priority)
        order.append(tag)

    low = asyncio.create_task(queued(0, "low"))
    await asyncio.sleep(0.01)  # low queues first
    high = asyncio.create_task(queued(2, "high"))
    await asyncio.sleep(0.01)
    assert ac.queue_depth() == 2
    ac.release()           # freed slot goes to high, despite arriving later
    await high
    ac.release()
    await low
    assert order == ["high", "low"]


@pytest.mark.asyncio
async def test_queue_full_and_deadline_reject_with_retry_after():
    ac = AdmissionController(
        AdmissionConfig(limit=1, queue_depth=1, queue_timeout_s=0.05,
                        retry_after_s=3.0),
        flight=FlightRecorder(16))
    await ac.acquire(1)
    waiting = asyncio.create_task(ac.acquire(1))
    await asyncio.sleep(0.01)
    # per-class queue bound: the second waiter is turned away immediately
    with pytest.raises(AdmissionRejected) as e:
        await ac.acquire(1)
    assert e.value.outcome == "queue_full"
    assert e.value.retry_after_header == "3"
    # the queued one hits the deadline
    with pytest.raises(AdmissionRejected) as e2:
        await waiting
    assert e2.value.outcome == "timeout"
    ac.release()
    assert ac.inflight == 0
    text = ac.registry.render()
    assert 'outcome="queue_full"' in text and 'outcome="timeout"' in text


@pytest.mark.asyncio
async def test_shed_level_rejects_and_flushes_only_low_classes():
    flight = FlightRecorder(64)
    ac = AdmissionController(
        AdmissionConfig(limit=1, queue_depth=8, queue_timeout_s=5.0),
        flight=flight)
    await ac.acquire(2)
    queued_low = asyncio.create_task(ac.acquire(0))
    queued_high = asyncio.create_task(ac.acquire(2))
    await asyncio.sleep(0.01)

    ac.set_shed_level(1)
    # the queued LOW waiter is flushed with the shed rejection...
    with pytest.raises(AdmissionRejected) as e:
        await queued_low
    assert e.value.outcome == "shed"
    # ...the queued HIGH waiter is untouched
    await asyncio.sleep(0.01)
    assert not queued_high.done()
    # new low arrivals shed at the door; normal and high still admitted
    with pytest.raises(AdmissionRejected):
        await ac.acquire(0)
    ac.release()
    await queued_high
    ac.release()
    # decisions are auditable in the flight ring
    assert any(e["kind"] == "planner.shed" for e in flight.snapshot())


@pytest.mark.asyncio
async def test_raising_limit_grants_queued_waiters():
    ac = AdmissionController(
        AdmissionConfig(limit=1, queue_depth=8, queue_timeout_s=5.0),
        flight=FlightRecorder(16))
    await ac.acquire(1)
    queued = asyncio.create_task(ac.acquire(1))
    await asyncio.sleep(0.01)
    assert not queued.done()
    ac.set_limit(2)
    await queued
    assert ac.inflight == 2


@pytest.mark.asyncio
async def test_cancelled_waiter_does_not_hold_queue_state():
    ac = AdmissionController(
        AdmissionConfig(limit=1, queue_depth=8, queue_timeout_s=5.0),
        flight=FlightRecorder(16))
    await ac.acquire(1)
    queued = asyncio.create_task(ac.acquire(1))
    await asyncio.sleep(0.01)
    queued.cancel()  # client disconnected while queued
    with pytest.raises(asyncio.CancelledError):
        await queued
    assert ac.queue_depth() == 0
    ac.release()  # freed slot must not be handed to the dead waiter
    assert ac.inflight == 0
    await ac.acquire(1)  # and the gate still works
    ac.release()


@pytest.mark.asyncio
async def test_snapshot_feeds_planner_signal_names():
    ac = AdmissionController(
        AdmissionConfig(limit=2, queue_depth=8, queue_timeout_s=5.0),
        flight=FlightRecorder(16))
    await ac.acquire(1)
    snap = ac.snapshot()
    assert snap["admission.inflight_ratio"] == 0.5
    assert snap["admission.queue_depth"] == 0.0
    assert snap["admission.shed_total"] == 0.0
    ac.release()


# --------------------------------------------------------------------------
# the traffic-spike e2e (acceptance criteria)
# --------------------------------------------------------------------------


class SlowDeterministicEngine:
    """OpenAI-level fake engine: fixed ids, fixed chunking, a scripted
    per-token delay — so two runs of the same prompt produce
    byte-identical SSE streams, loaded or not."""

    def __init__(self, token_delay_s: float = 0.02):
        self.token_delay_s = token_delay_s
        self.active = 0
        self.peak_active = 0

    async def generate(self, ctx):
        req = ctx.payload
        words = req.messages[-1].text_content().split()
        self.active += 1
        self.peak_active = max(self.peak_active, self.active)
        try:
            for i, word in enumerate(words):
                await asyncio.sleep(self.token_delay_s)
                yield {
                    "id": "chatcmpl-fixed",
                    "object": "chat.completion.chunk",
                    "created": 1,
                    "model": req.model,
                    "choices": [{
                        "index": 0,
                        "delta": {"content": ("" if i == 0 else " ") + word},
                        "finish_reason": None,
                    }],
                }
            yield {
                "id": "chatcmpl-fixed",
                "object": "chat.completion.chunk",
                "created": 1,
                "model": req.model,
                "choices": [{"index": 0, "delta": {},
                             "finish_reason": "stop"}],
            }
        finally:
            self.active -= 1


def _spike_cr():
    return {
        "apiVersion": "dynamo.tpu/v1alpha1",
        "kind": "DynamoTpuGraphDeployment",
        "metadata": {"name": "spike", "namespace": "serving", "uid": "u-1"},
        "spec": {
            "image": "dynamo-tpu:test",
            "namespace": "public",
            "services": {
                "decode": {"role": "decode", "replicas": 1,
                           "modelPath": "/m"},
                "prefill": {"role": "prefill", "replicas": 1,
                            "modelPath": "/m"},
            },
        },
    }


async def _post_chat(session, port, prompt, priority, rid):
    """One streamed chat request; returns (status, raw_sse_bytes,
    ttft_s, retry_after_header)."""
    loop = asyncio.get_running_loop()
    t0 = loop.time()
    ttft = None
    raw = b""
    async with session.post(
        f"http://127.0.0.1:{port}/v1/chat/completions",
        json={"model": "slow",
              "messages": [{"role": "user", "content": prompt}],
              "stream": True},
        headers={"X-Priority": priority, "X-Request-Id": rid},
    ) as r:
        async for chunk in r.content.iter_any():
            if ttft is None and b'"content"' in chunk:
                ttft = loop.time() - t0
            raw += chunk
        return r.status, raw, ttft, r.headers.get("Retry-After")


@pytest.mark.asyncio
async def test_traffic_spike_sheds_low_scales_up_and_keeps_high_identical():
    """ISSUE 6 acceptance: spike → only the lowest class sheds, queued
    high-priority TTFT holds the deadline, and the planner's scale-up
    lands in InMemoryKube — one end-to-end test."""
    engine = SlowDeterministicEngine(token_delay_s=0.02)
    manager = ModelManager()
    manager.add_chat_model("slow", engine)
    flight = FlightRecorder(256)
    deadline_s = 3.0
    admission = AdmissionController(
        AdmissionConfig(limit=2, queue_depth=16, queue_timeout_s=deadline_s,
                        retry_after_s=2.0),
        flight=flight)
    service = HttpService(manager, host="127.0.0.1", port=0,
                          admission=admission)

    # planner: admission state is the saturation signal; actions land in
    # an in-memory cluster (scale) and back on the admission gate (shed)
    kube = InMemoryKube()
    cr = _spike_cr()
    kube_actuator = KubeActuator(Reconciler(kube), cr)
    policy = SlaPolicy(PolicyConfig(
        window_s=10.0,
        decode_busy_up=0.9, decode_waiting_up=2.0,
        saturation_busy=0.9, saturation_waiting=3.0,
        min_replicas=1, max_replicas=4,
        scale_up_cooldown_s=0.0, shed_step_cooldown_s=0.0,
    ))
    planner = Planner(
        policy=policy,
        sources=[
            admission.snapshot,
            lambda: {
                "decode.slot_busy_ratio": (
                    admission.inflight / admission.limit
                    if admission.limit else 0.0),
                "decode.waiting": float(admission.queue_depth()),
            },
        ],
        actuators=[kube_actuator, LocalActuator(admission=admission)],
        flight=flight,
    )

    await service.start()
    prompt = "alpha beta gamma delta"
    try:
        timeout = aiohttp.ClientTimeout(total=30)
        async with aiohttp.ClientSession(timeout=timeout) as s:
            # ---- baseline: one unloaded high-priority stream ----
            status, baseline_raw, _, _ = await _post_chat(
                s, service.port, prompt, "high", "base-0")
            assert status == 200

            # ---- occupy both slots with long high-priority streams, so
            # the spike below queues deterministically ----
            long_prompt = " ".join(f"tok{i}" for i in range(20))
            occupiers = [
                asyncio.create_task(_post_chat(
                    s, service.port, long_prompt, "high", f"occ-{i}"))
                for i in range(2)
            ]
            for _ in range(100):  # until both are admitted and streaming
                await asyncio.sleep(0.01)
                if admission.inflight == 2:
                    break
            assert admission.inflight == 2

            # ---- spike: 6 low + 4 high land together; 0 free slots ----
            spike = [
                _post_chat(s, service.port, prompt, "low", f"low-{i}")
                for i in range(6)
            ] + [
                _post_chat(s, service.port, prompt, "high", f"high-{i}")
                for i in range(4)
            ]
            tasks = [asyncio.create_task(c) for c in spike]
            for _ in range(100):  # until the whole spike is queued
                await asyncio.sleep(0.01)
                if admission.queue_depth() == 10:
                    break
            assert admission.queue_depth() == 10

            # planner observes the saturation and acts: shed + scale-up
            actions = await planner.step()
            assert any(isinstance(a, ScaleAction) for a in actions)
            assert policy.shed_level >= 1
            assert admission.shed_level >= 1

            results = await asyncio.gather(*tasks)
            low_results, high_results = results[:6], results[6:]
            for status, _raw, _t, _ra in await asyncio.gather(*occupiers):
                assert status == 200
            # the admission limit actually bounded engine concurrency
            assert engine.peak_active <= 2

            # only the lowest class shed: every queued low got 429 +
            # Retry-After, every high completed
            for status, raw, _, retry_after in low_results:
                assert status == 429
                assert retry_after is not None and int(retry_after) >= 1
                assert b"shed" in raw or b"saturated" in raw
            for status, raw, ttft, _ in high_results:
                assert status == 200
                # queued TTFT under the configured admission deadline
                assert ttft is not None and ttft < deadline_s
                # byte-identical to the unloaded baseline stream
                assert raw == baseline_raw

            # the scale-up action landed as a replica patch in the
            # in-memory cluster
            dep = kube.objects["Deployment/serving/spike-decode"]
            assert dep["spec"]["replicas"] == 2

            # high priority was never shed
            text = service.metrics.render()
            assert 'priority="low",outcome="shed"' not in text  # label order
            assert ('dynamo_planner_admissions_total{outcome="shed",'
                    'priority="low"}') in text
            assert 'outcome="shed",priority="high"' not in text
            assert 'outcome="timeout"' not in text

            # decisions auditable in the flight ring: shed events carry
            # the request ids, and the planner action timeline is there
            events = flight.snapshot()
            shed_ids = {e.get("request_id") for e in events
                        if e["kind"] == "planner.shed"}
            assert any(rid and rid.startswith("low-") for rid in shed_ids)
            assert not any(rid and rid.startswith("high-")
                           for rid in shed_ids)
            assert any(e["kind"] == "planner.action"
                       and e["data"]["action"] == "scale"
                       for e in events)

            # after the spike drains, recovery: relax the gate and a
            # fresh low-priority request is admitted again
            admission.set_shed_level(0)
            status, raw, _, _ = await _post_chat(
                s, service.port, prompt, "low", "recovered-0")
            assert status == 200 and raw == baseline_raw
    finally:
        await service.stop()


@pytest.mark.asyncio
async def test_http_service_without_admission_unchanged():
    """No admission controller configured → no 429 path, no header
    requirement (the default construction stays byte-compatible)."""
    engine = SlowDeterministicEngine(token_delay_s=0.0)
    manager = ModelManager()
    manager.add_chat_model("slow", engine)
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    try:
        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"http://127.0.0.1:{service.port}/v1/chat/completions",
                json={"model": "slow",
                      "messages": [{"role": "user", "content": "hi there"}],
                      "stream": True},
            ) as r:
                assert r.status == 200
                await r.read()
    finally:
        await service.stop()


@pytest.mark.asyncio
async def test_timed_out_waiters_leave_the_queue():
    """A sustained retry storm (every client re-queueing each deadline)
    must not accumulate abandoned waiter objects in the deques."""
    ac = AdmissionController(
        AdmissionConfig(limit=1, queue_depth=4, queue_timeout_s=0.02),
        flight=FlightRecorder(16))
    await ac.acquire(1)  # hold the only slot
    for _ in range(10):
        with pytest.raises(AdmissionRejected):
            await ac.acquire(1)
    # every timed-out waiter was discarded, not just flagged
    assert sum(len(q) for q in ac._queues.values()) == 0
    ac.release()
    assert ac.inflight == 0
