"""DeepSeek-class MLA: absorption math, cache compression, engine integration."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.models import deepseek, resolve
from dynamo_tpu.models.llama import apply_rope, rms_norm

MLA_CFG = dict(
    vocab_size=256, hidden_size=64, intermediate_size=96, num_layers=2,
    num_heads=4, num_kv_heads=4, head_dim=16,
    kv_lora_rank=16, qk_rope_head_dim=8, qk_nope_head_dim=12, v_head_dim=12,
)


def test_registry_resolves_mla():
    assert resolve(ModelConfig(**MLA_CFG)) is deepseek


def test_partial_mla_config_rejected():
    with pytest.raises(ValueError, match="v_head_dim"):
        ModelConfig(kv_lora_rank=8)
    with pytest.raises(ValueError, match="qk_rope_head_dim"):
        ModelConfig(kv_lora_rank=8, qk_nope_head_dim=8, v_head_dim=8)


def test_cache_is_compressed():
    """Per-token cache line is r + rope_dim, independent of heads."""
    cfg = ModelConfig(**MLA_CFG)
    c, kr = deepseek.init_kv_cache(cfg, num_blocks=8, block_size=4)
    # minor dims are lane-padded to 128 (physically free in the tiled HBM
    # layout; required by the manual-DMA decode kernel)
    assert c.shape == (2, 8, 4, 1, 128)   # lane_pad(kv_lora_rank=16)
    assert kr.shape == (2, 8, 4, 1, 128)  # lane_pad(qk_rope_head_dim=8)
    # vs a GQA cache of the same config: 2 * kvh * head_dim per token
    mla_line = 16 + 8
    gqa_line = 2 * 4 * 16
    assert mla_line < gqa_line / 5


def test_absorbed_attention_matches_explicit():
    """score = (q W_uk)·c + q_r·k_r must equal attention with materialized
    per-head K/V (k = c W_uk, v = c W_uv) — the absorption identity."""
    key = jax.random.PRNGKey(0)
    b, s, h, r, nope, rd, vd = 1, 6, 3, 8, 5, 4, 7
    ks = jax.random.split(key, 6)
    q_nope = jax.random.normal(ks[0], (b, s, h, nope))
    q_rope = jax.random.normal(ks[1], (b, s, h, rd))
    c = jax.random.normal(ks[2], (b, s, r))          # latent per token
    kr = jax.random.normal(ks[3], (b, s, rd))        # shared rope key
    w_uk = jax.random.normal(ks[4], (r, h, nope))
    w_uv = jax.random.normal(ks[5], (r, h, vd))
    scale = (nope + rd) ** -0.5

    # absorbed path, via the paged kernel (one block holding the whole seq)
    c_cache = c.reshape(1, s, 1, r)
    kr_cache = kr.reshape(1, s, 1, rd)
    btab = jnp.zeros((b, 1), jnp.int32)
    pos = jnp.arange(s)[None, :]
    q_lat = jnp.einsum("bshn,rhn->bshr", q_nope, w_uk)
    o_lat = deepseek.mla_paged_attention(
        q_lat, q_rope, c_cache, kr_cache, btab, pos, jnp.asarray([s]), scale
    )
    got = jnp.einsum("bshr,rhv->bshv", o_lat, w_uv)

    # explicit path: materialize k/v per head
    k_nope = jnp.einsum("btr,rhn->bthn", c, w_uk)
    v = jnp.einsum("btr,rhv->bthv", c, w_uv)
    scores = (
        jnp.einsum("bshn,bthn->bsht", q_nope, k_nope)
        + jnp.einsum("bshd,btd->bsht", q_rope, kr)
    ) * scale
    mask = jnp.arange(s)[None, None, :] <= pos[:, :, None]
    scores = jnp.where(mask[:, :, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    want = jnp.einsum("bsht,bthv->bshv", probs, v)

    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("q_lora_rank", [0, 12])
def test_mla_forward_prefill_decode_consistency(q_lora_rank):
    cfg = ModelConfig(**{**MLA_CFG, "q_lora_rank": q_lora_rank})
    params = deepseek.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    cache = deepseek.init_kv_cache(cfg, 16, 4, jnp.float32)

    s = 8
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, s), 0, 256)
    pos = jnp.arange(s)[None, :]
    btab = jnp.arange(4)[None, :]
    slot = pos
    logits_all, _ = deepseek.forward(
        params, cfg, tokens, pos, cache, btab, slot, jnp.asarray([s])
    )
    logits_pre, cache2 = deepseek.forward(
        params, cfg, tokens[:, : s - 1], pos[:, : s - 1], cache, btab,
        slot[:, : s - 1], jnp.asarray([s - 1]),
    )
    logits_dec, _ = deepseek.forward(
        params, cfg, tokens[:, s - 1 :], pos[:, s - 1 :], cache2, btab,
        slot[:, s - 1 :], jnp.asarray([s]),
    )
    np.testing.assert_allclose(
        np.asarray(logits_all[0, -1]), np.asarray(logits_dec[0, -1]),
        rtol=2e-3, atol=2e-3,
    )


def test_mla_moe_combination():
    """DeepSeek-V2/V3 shape: MLA attention + routed experts."""
    cfg = ModelConfig(**{**MLA_CFG, "num_experts": 4, "num_experts_per_tok": 2})
    assert resolve(cfg) is deepseek
    params = deepseek.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    assert "router" in params["layers"]
    cache = deepseek.init_kv_cache(cfg, 8, 4, jnp.float32)
    tokens = jnp.asarray([[1, 2, 3, 4]])
    pos = jnp.arange(4)[None, :]
    logits, _ = deepseek.forward(
        params, cfg, tokens, pos, cache, jnp.asarray([[0, 1]]), pos,
        jnp.asarray([4]),
    )
    assert logits.shape == (1, 4, 256)
    assert np.all(np.isfinite(np.asarray(logits)))


@pytest.mark.parametrize("tp", [1, 2])
def test_model_runner_mla_tp(tp):
    """Engine step with MLA: heads shard over tp, latent cache replicated."""
    from dynamo_tpu.engine.model_runner import ModelRunner, build_mesh

    mcfg = ModelConfig(**MLA_CFG)
    cfg = EngineConfig(
        model=mcfg, max_batch_size=2, max_model_len=64, kv_block_size=8,
        num_kv_blocks=32, dtype="float32", dp_size=1, tp_size=tp,
        prefill_buckets=[64],
    )
    runner = ModelRunner(cfg, mesh=build_mesh(1, tp, jax.devices()[:tp]))
    b, w, bs = cfg.max_batch_size, cfg.blocks_per_seq, cfg.kv_block_size
    s = 8
    tokens = np.random.RandomState(0).randint(0, 256, (b, s)).astype(np.int32)
    positions = np.tile(np.arange(s, dtype=np.int32), (b, 1))
    btab = np.zeros((b, w), np.int32)
    for i in range(b):
        btab[i, 0] = i
    slot_map = btab[:, :1] * bs + positions
    next_tokens, *_ = runner.step(
        tokens, positions, btab, slot_map, np.full(b, s, np.int32),
        np.full(b, s - 1, np.int32), np.zeros(b, np.float32),
        np.zeros(b, np.int32), np.ones(b, np.float32), jax.random.PRNGKey(0),
    )
    assert np.asarray(next_tokens).shape == (b,)


def test_hf_config_mla_mapping():
    cfg = ModelConfig.from_hf_config({
        "hidden_size": 128, "kv_lora_rank": 64, "q_lora_rank": 32,
        "qk_rope_head_dim": 16, "qk_nope_head_dim": 32, "v_head_dim": 32,
        "n_routed_experts": 8, "moe_intermediate_size": 48,
        "n_shared_experts": 2, "first_k_dense_replace": 1,
    })
    assert cfg.kv_lora_rank == 64 and cfg.q_lora_rank == 32
    assert cfg.num_experts == 8
    assert cfg.moe_intermediate_size == 48
    assert cfg.n_shared_experts == 2
    assert cfg.first_k_dense_replace == 1
    assert resolve(cfg) is deepseek


def test_deepseek_v2_topology():
    """first_k dense layers, MoE layers with shared experts at moe width."""
    cfg = ModelConfig(**{
        **MLA_CFG, "num_layers": 3, "num_experts": 4,
        "moe_intermediate_size": 32, "n_shared_experts": 1,
        "first_k_dense_replace": 1,
    })
    params = deepseek.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    # 1 dense layer at full width, 2 MoE layers at moe width
    assert params["dense_layers"]["w_gate"].shape == (1, 64, 96)
    assert params["layers"]["w_gate"].shape == (2, 4, 64, 32)
    assert params["layers"]["w_sh_gate"].shape == (2, 64, 32)

    cache = deepseek.init_kv_cache(cfg, 8, 4, jnp.float32)
    tokens = jnp.asarray([[1, 2, 3, 4, 5, 6]])
    pos = jnp.arange(6)[None, :]
    logits, _ = deepseek.forward(
        params, cfg, tokens, pos, cache, jnp.asarray([[0, 1]]), pos,
        jnp.asarray([6]),
    )
    assert logits.shape == (1, 6, 256)
    assert np.all(np.isfinite(np.asarray(logits)))
    # specs cover every param
    specs = deepseek.param_specs(params)
    jax.tree.map(lambda a, s: None, params, specs,
                 is_leaf=lambda x: x is None)
