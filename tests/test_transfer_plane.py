"""Unified transfer plane (dynamo_tpu/transfer/): primitives + loopback
ICI differentials.

The acceptance contract: every plane (disagg push, fabric prefix pull,
hot migration) rides the same framing/poison/pipelining core, the ici
backend produces BYTE-IDENTICAL streams to tcp with zero leaked blocks
or pins on either side, a backend dying mid-stream degrades (balancing
or abandonment per the pairing discipline) without corrupting anything,
and ``DYN_FAULT=transfer_conn_drop`` drops connections through the one
shared chaos seam.
"""

import asyncio
import struct

import numpy as np
import pytest

from dynamo_tpu.engine.scheduler import Scheduler
from dynamo_tpu.recovery import (
    MigrationServer,
    MigrationSink,
    RecoveryConfig,
    RecoveryController,
)
from dynamo_tpu.telemetry.flight import FlightRecorder, flight_recorder
from dynamo_tpu.telemetry.registry import MetricsRegistry
from dynamo_tpu.transfer import (
    MAX_HEADER,
    FramePipe,
    IciBackend,
    LoopbackIciTransfer,
    PoisonSet,
    TcpBackend,
    maybe_drop_connection,
    negotiate_backend,
    pack_frame,
    read_exact,
    read_header,
)
from dynamo_tpu.transfer.framing import decode_blocks, encode_blocks
from dynamo_tpu.transfer.plane import TransferMetrics
from dynamo_tpu.utils import faults

from test_jax_engine import hf_model_dir, TINY  # noqa: F401
from test_kv_fabric import (
    SHARED_PREFIX,
    _assert_no_leaks,
    _engine,
    _events,
    _run_one,
    _wire_a_to_b,
)
from test_recovery import (
    MigRunner,
    _baseline,
    _collect,
    _config,
    _request,
)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


# --------------------------------------------------------------------------
# framing: the one wire format all three planes share
# --------------------------------------------------------------------------


class _BufWriter:
    def __init__(self):
        self.chunks = []

    def write(self, b):
        self.chunks.append(bytes(b))

    def bytes(self):
        return b"".join(self.chunks)


def _reader_over(raw: bytes) -> asyncio.StreamReader:
    r = asyncio.StreamReader()
    r.feed_data(raw)
    r.feed_eof()
    return r


async def test_framing_roundtrip_and_clean_eof():
    w = _BufWriter()
    pack_frame(w, {"type": "blocks", "seq": 3}, b"abcde", b"xy")
    pack_frame(w, {"type": "commit"})
    r = _reader_over(w.bytes())
    h = await read_header(r, "t")
    assert h == {"type": "blocks", "seq": 3}
    assert await read_exact(r, 5) == b"abcde"
    assert await read_exact(r, 2) == b"xy"
    assert (await read_header(r, "t")) == {"type": "commit"}
    # a clean EOF at a frame boundary is None, not an exception — the
    # callers that need failure semantics raise on None explicitly
    assert await read_header(r, "t") is None


async def test_framing_rejects_oversized_header():
    r = _reader_over(struct.pack(">I", MAX_HEADER + 1) + b"\x00" * 8)
    with pytest.raises(ValueError):
        await read_header(r, "t")


def test_encode_decode_blocks_roundtrip_incl_bfloat16():
    import ml_dtypes

    for dtype in (np.float32, ml_dtypes.bfloat16):
        k = np.arange(24, dtype=np.float32).reshape(1, 2, 3, 1, 4)
        k = k.astype(dtype)
        v = (k + 1).astype(dtype)
        kb, vb, shape, dname = encode_blocks(k, v)
        k2, v2 = decode_blocks(kb, vb, shape, dname)
        np.testing.assert_array_equal(np.asarray(k2), np.asarray(k))
        np.testing.assert_array_equal(np.asarray(v2), np.asarray(v))
        assert k2.shape == k.shape and k2.dtype == k.dtype


# --------------------------------------------------------------------------
# plane primitives: poison, pipelining bound, negotiation, chaos seam
# --------------------------------------------------------------------------


def test_poison_set_marks_nacks_once_and_expires(monkeypatch):
    from dynamo_tpu.transfer import plane as plane_mod

    now = [1000.0]
    monkeypatch.setattr(plane_mod.time, "monotonic", lambda: now[0])
    ps = PoisonSet("disagg")
    ps.mark("r1", backend="ici", reason="seq_mismatch")
    assert "r1" in ps
    # one commit consumes the mark (nack-once): a retried request id
    # must not stay poisoned forever
    assert ps.pop("r1") is True
    assert ps.pop("r1") is False
    # TTL expiry: a mark older than DROPPED_TTL_S is pruned on the next
    # insert — no commit can still arrive for it
    ps.mark("old")
    now[0] += plane_mod.DROPPED_TTL_S + 1
    ps.mark("new")
    assert "old" not in ps and "new" in ps


async def test_frame_pipe_bounds_live_frames_at_two():
    """The pipelining acceptance: maxsize=1 + one-frame pump lookahead
    means at most TWO frames exist between producer and wire at any
    instant, regardless of how many chunks the sequence has."""
    pipe = FramePipe(depth=2, frame_blocks=4)
    drained = []

    async def pump():
        while True:
            f = await pipe.q.get()
            if f is None:
                return
            await asyncio.sleep(0.005)  # slow wire: producer must block
            drained.append(f)
            pipe.nbytes += 1

    pipe.task = asyncio.ensure_future(pump())
    max_outstanding = 0
    for i in range(6):
        await pipe.put(i)
        max_outstanding = max(max_outstanding, (i + 1) - len(drained))
    assert await pipe.drain() == 6
    assert drained == list(range(6)), "frames lost or reordered"
    assert max_outstanding <= 2, \
        f"{max_outstanding} frames in flight — pipelining bound broken"


async def test_frame_pipe_surfaces_pump_error_on_put():
    pipe = FramePipe(depth=2, frame_blocks=4)

    async def pump():
        await pipe.q.get()
        pipe.error = ConnectionResetError("wire died")
        # drain the queue so a blocked producer wakes to see the error
        while not pipe.q.empty():
            pipe.q.get_nowait()

    pipe.task = asyncio.ensure_future(pump())
    await pipe.put(0)
    await pipe.task
    with pytest.raises(ConnectionResetError):
        await pipe.put(1)
    await pipe.shutdown()


def test_negotiate_backend_matrix():
    ici = IciBackend(LoopbackIciTransfer(sender_rank=0, receiver_rank=1))
    # no local plane, or an abandoned one → tcp always
    assert negotiate_backend({"modes": ["tcp", "ici"]}, None) == "tcp"
    dead = IciBackend(LoopbackIciTransfer())
    dead.abandon()
    assert negotiate_backend({"modes": ["tcp", "ici"]}, dead) == "tcp"
    # peer doesn't advertise ici (or predates modes) → tcp
    assert negotiate_backend({"modes": ["tcp"]}, ici) == "tcp"
    assert negotiate_backend({}, ici) == "tcp"
    assert negotiate_backend(None, ici) == "tcp"
    # rank mismatch = a different mesh: entering would strand both sides
    assert negotiate_backend(
        {"modes": ["tcp", "ici"], "ici_rank": 7}, ici,
        peer_role="receiver") == "tcp"
    # matching rank per role
    assert negotiate_backend(
        {"modes": ["tcp", "ici"], "ici_rank": 1}, ici,
        peer_role="receiver") == "ici"
    assert negotiate_backend(
        {"modes": ["tcp", "ici"], "ici_rank": 0}, ici,
        peer_role="sender") == "ici"
    # no rank advertised → trust the mode flag (pre-rank descriptors)
    assert negotiate_backend({"modes": ["tcp", "ici"]}, ici) == "ici"


def test_conn_drop_fault_fires_through_the_shared_seam():
    """DYN_FAULT=transfer_conn_drop is rewired to the one chaos seam
    every plane's chunk loop consults."""
    assert maybe_drop_connection("disagg") is False
    faults.arm("transfer_conn_drop", "once")
    assert maybe_drop_connection("fabric") is True
    assert maybe_drop_connection("migration") is False  # one-shot


def _global_flight_watermark():
    """record_open/PoisonSet record into the process-global flight ring
    (planes outlive any one scheduler); return a seq watermark so a test
    only reads its own events."""
    events = flight_recorder().snapshot()
    return events[-1]["seq"] if events else -1


def _global_flight_since(seq0, kind):
    return [{**e.get("data", {}), **e}
            for e in flight_recorder().snapshot()
            if e["seq"] > seq0 and e.get("kind") == kind]


def test_transfer_metrics_single_family_with_plane_backend_labels():
    reg = MetricsRegistry()
    m = TransferMetrics(reg, plane="fabric")
    m.add_bytes(64, "ici")
    m.add_bytes(32, "tcp", plane="migration")
    m.observe_duration(0.5, "ici")
    m.observe_exposed(0.1, "ici")
    m.channel_opened("ici")
    m.channel_closed("ici")
    out = reg.render()
    assert "dynamo_transfer_bytes_total" in out
    assert 'plane="fabric"' in out and 'backend="ici"' in out
    assert 'plane="migration"' in out and 'backend="tcp"' in out
    assert "dynamo_transfer_duration_seconds" in out
    assert "dynamo_transfer_exposed_seconds" in out
    assert "dynamo_transfer_channels" in out
    # the retired per-plane families must NOT be re-registered anywhere
    for retired in ("dynamo_disagg_transfer_duration_seconds",
                    "dynamo_kv_fabric_prefix_pull_bytes_total",
                    "dynamo_prefill_worker_transfer_bytes_total"):
        assert retired not in out


# --------------------------------------------------------------------------
# ici backend discipline (loopback: full pairing semantics, no mesh)
# --------------------------------------------------------------------------


async def test_loopback_ici_send_recv_pairs_and_crosschecks_seq():
    lb = LoopbackIciTransfer()
    tx, rx = IciBackend(lb), IciBackend(lb, recv_timeout_s=5.0)
    k = np.arange(8, dtype=np.float32).reshape(1, 2, 2, 1, 2)
    v = k + 10
    seq = tx.next_seq()
    sent_task = asyncio.ensure_future(tx.send(k, v, seq, 2))
    rk, rv, rseq = await rx.recv(2)
    assert await sent_task == k.nbytes + v.nbytes
    assert rseq == seq
    np.testing.assert_array_equal(rk, k)
    np.testing.assert_array_equal(rv, v)


async def test_pre_entry_send_failure_balances_and_keeps_plane():
    """A failure BEFORE entering the collective leaves the receiver an
    unpaired entry: the sender pairs it with a poison payload (seq -1)
    and the plane REMAINS usable for the retry."""
    lb = LoopbackIciTransfer()
    tx, rx = IciBackend(lb), IciBackend(lb, recv_timeout_s=5.0)
    lb.fail_next_send = "pre"
    k = np.zeros((1, 2, 2, 1, 2), np.float32)
    with pytest.raises(Exception):
        await tx.send(k, k, tx.next_seq(), 2)
    assert tx.alive, "pre-entry failure must not abandon the plane"
    assert lb.balanced == 1
    _, _, seq = await rx.recv(2)
    assert seq == -1, "poison payload must never match a real header"
    # the retry pairs cleanly
    seq2 = tx.next_seq()
    sent = asyncio.ensure_future(tx.send(k, k, seq2, 2))
    assert (await rx.recv(2))[2] == seq2
    await sent


async def test_post_entry_send_failure_abandons_plane():
    lb = LoopbackIciTransfer()
    tx = IciBackend(lb)
    lb.fail_next_send = "post"
    k = np.zeros((1, 2, 2, 1, 2), np.float32)
    with pytest.raises(Exception):
        await tx.send(k, k, tx.next_seq(), 2)
    assert not tx.alive, "entered-collective failure must abandon"
    assert negotiate_backend({"modes": ["tcp", "ici"]}, tx) == "tcp"


async def test_recv_timeout_abandons_plane_receiver_side():
    rx = IciBackend(LoopbackIciTransfer(), recv_timeout_s=0.05)
    with pytest.raises(asyncio.TimeoutError):
        await rx.recv(2)  # nothing was ever sent
    assert not rx.alive


# --------------------------------------------------------------------------
# fabric prefix pull over loopback ici: the byte-identity differential
# --------------------------------------------------------------------------


async def _two_engine_ici_rig(hf_model_dir, recv_timeout_s=10.0):
    """test_kv_fabric's two-engine rig with a shared loopback collective
    plane: A serves pulls with its sender half, B receives with the
    receiver half, and the peer descriptor advertises the mode + rank
    so negotiation picks ici."""
    lb = LoopbackIciTransfer(sender_rank=0, receiver_rank=1)
    sched_b = _engine(hf_model_dir)
    sched_a = _engine(hf_model_dir, events=_wire_a_to_b(sched_b))
    sched_a.fabric.set_ici(IciBackend(lb))
    sched_b.fabric.set_ici(IciBackend(lb, recv_timeout_s=recv_timeout_s))
    server_a = await sched_a.fabric.serve()
    desc = dict(server_a.descriptor)
    assert "ici" in desc["modes"], "serve half must advertise the plane"
    sched_b.fabric.peers = (lambda: {"worker-a": desc})
    sched_a.start()
    sched_b.start()
    return sched_a, sched_b, lb


def _spy_tcp_payloads(monkeypatch):
    """Count TcpBackend payload moves — the ici differential must show
    ZERO (headers ride tcp, payloads never do)."""
    calls = {"send": 0, "recv": 0}
    real_send, real_recv = TcpBackend.send_blocks, TcpBackend.recv_blocks

    async def spy_send(*a, **kw):
        calls["send"] += 1
        return await real_send(*a, **kw)

    async def spy_recv(*a, **kw):
        calls["recv"] += 1
        return await real_recv(*a, **kw)

    monkeypatch.setattr(TcpBackend, "send_blocks", spy_send)
    monkeypatch.setattr(TcpBackend, "recv_blocks", spy_recv)
    return calls


async def test_fabric_pull_over_ici_byte_identical(hf_model_dir,
                                                   monkeypatch):
    """The headline fabric differential: the same pull that commits over
    tcp commits over loopback ici with a BYTE-IDENTICAL stream, zero
    leaked blocks/pins on both sides, and the payload never touching
    the host (no TcpBackend block move, device arrays scattered)."""
    prompt_a = SHARED_PREFIX + [30, 31, 32, 33, 34, 35]
    prompt_b = SHARED_PREFIX + [40, 41, 42, 43, 44, 45]

    sched_base = _engine(hf_model_dir)
    sched_base.start()
    baseline = await _run_one(sched_base, prompt_b, "base")
    await sched_base.stop()

    tcp_calls = _spy_tcp_payloads(monkeypatch)
    seq0 = _global_flight_watermark()
    sched_a, sched_b, lb = await _two_engine_ici_rig(hf_model_dir)
    scattered_types = []
    real_scatter = sched_b.runner.scatter_blocks

    def spy_scatter(ids, k, v):
        scattered_types.append(type(k))
        return real_scatter(ids, k, v)

    sched_b.runner.scatter_blocks = spy_scatter
    try:
        await _run_one(sched_a, prompt_a, "warm")
        out = await _run_one(sched_b, prompt_b, "pulled")
        assert out == baseline, "ici pull diverged from recompute"
        pulls = _events(sched_b, "kv_fabric.pull")
        assert pulls and pulls[-1]["backend"] == "ici"
        assert pulls[-1]["outcome"] == "committed"
        opens = [e for e in _global_flight_since(seq0, "transfer.open")
                 if e["plane"] == "fabric"]
        assert opens and opens[-1]["backend"] == "ici"
        assert lb.sent >= 1, "no collective ever entered"
        # zero-copy contract: payload frames never rode tcp, and what
        # reached the cache was device arrays, not host ndarrays
        assert tcp_calls == {"send": 0, "recv": 0}
        assert scattered_types and all(
            t is not np.ndarray for t in scattered_types)
        _assert_no_leaks(sched_b)
    finally:
        await sched_a.stop()
        await sched_b.stop()
    _assert_no_leaks(sched_a)


async def test_fabric_pull_ici_death_falls_back_byte_identical(
        hf_model_dir):
    """Mid-stream backend death: the serving side's collective fails
    pre-entry — the balancing poison entry mis-matches the header seq on
    the puller, the pull aborts (never scattering unknown bytes), and
    the request falls back to local recompute byte-identically with
    zero leaks. The plane survives (pre-entry discipline)."""
    prompt_a = SHARED_PREFIX + [30, 31, 32, 33, 34, 35]
    prompt_b = SHARED_PREFIX + [40, 41, 42, 43, 44, 45]

    sched_base = _engine(hf_model_dir)
    sched_base.start()
    baseline = await _run_one(sched_base, prompt_b, "base")
    await sched_base.stop()

    sched_a, sched_b, lb = await _two_engine_ici_rig(hf_model_dir)
    try:
        await _run_one(sched_a, prompt_a, "warm")
        lb.fail_next_send = "pre"
        out = await _run_one(sched_b, prompt_b, "dropped")
        assert out == baseline
        assert _events(sched_b, "kv_fabric.local_fallback"), \
            "expected a local fallback after the collective death"
        assert sched_a.fabric.ici.alive, \
            "pre-entry failure must keep the plane (balancing, not " \
            "abandonment)"
        _assert_no_leaks(sched_b)
    finally:
        await sched_a.stop()
        await sched_b.stop()
    _assert_no_leaks(sched_a)


# --------------------------------------------------------------------------
# hot migration over loopback ici
# --------------------------------------------------------------------------


class IciMigRunner(MigRunner):
    """MigRunner + the device-gather surface the ici path negotiates
    on. Returns jax device arrays — the loopback passes them by
    reference, so a host ndarray anywhere downstream means the
    zero-copy contract broke."""

    def gather_blocks_device(self, block_ids):
        import jax.numpy as jnp

        bs = self.config.kv_block_size
        shape = (1, len(block_ids), bs, 1, 4)
        return jnp.zeros(shape, jnp.float16), jnp.zeros(shape, jnp.float16)


def _drive_ici_migration(chaos=None, max_tokens=48):
    """Admin-drain a live request across two engines with the migration
    plane negotiated onto loopback ici. ``chaos``: None | "pre" (first
    collective fails before pairing → balancing + peer failover) |
    "post" (fails after entering → plane abandoned → tcp failover)."""
    config = _config()
    prompt = [1, 17, 43]
    out = {}
    seq0 = _global_flight_watermark()

    async def go():
        lb = LoopbackIciTransfer(sender_rank=0, receiver_rank=1)
        src_ici = IciBackend(lb)
        src_runner = IciMigRunner(config, sync_delay=0.02)
        dst_runner = MigRunner(config)
        src = Scheduler(src_runner, config, flight=FlightRecorder())
        dst = Scheduler(dst_runner, config, flight=FlightRecorder())
        src.start()
        dst.start()
        server = await MigrationServer(
            MigrationSink(dst, dst_runner),
            ici=IciBackend(lb, recv_timeout_s=5.0), ici_rank=1,
        ).start()
        desc = dict(server.descriptor, engine_id="dst")
        assert "ici" in desc["modes"] and desc["ici_rank"] == 1
        peers = [desc, desc] if chaos else [desc]
        controller = RecoveryController(
            engine_id="src", scheduler=src, runner=src_runner,
            peers=lambda: peers,
            config=RecoveryConfig(drain_grace_s=0.05),
            flight=src.flight, ici=src_ici,
        )
        er = _request(prompt, max_tokens)
        src.add_request(er)
        toks, finish = await _collect(er, limit=6)
        assert finish is None, "request finished before the drain"
        if chaos:
            lb.fail_next_send = chaos
        out["summary"] = await controller.drain(hard=False, reason="admin")
        rest, finish = await _collect(er)
        out["toks"], out["finish"] = toks + rest, finish
        out["sent"] = lb.sent
        out["balanced"] = lb.balanced
        out["src_ici_alive"] = src_ici.alive
        out["src_used"] = src.allocator.used
        out["dst_scattered"] = list(dst_runner.scattered)
        out["metrics"] = controller.registry.render()
        await controller.close()
        await server.close()
        await dst.stop()
        await src.stop()
        # the abort path frees asynchronously with the connection close
        for _ in range(50):
            if dst.allocator.used == 0:
                break
            await asyncio.sleep(0.02)
        out["dst_used"] = dst.allocator.used

    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(go())
    finally:
        loop.close()
    out["opens"] = [e.get("data", {})
                    for e in flight_recorder().snapshot()
                    if e["seq"] > seq0 and e.get("kind") == "transfer.open"
                    and e.get("data", {}).get("plane") == "migration"]
    out["want"] = _baseline(prompt, max_tokens)
    return out


def test_hot_migration_over_ici_byte_identical(monkeypatch):
    """The headline migration differential: a hot drain whose KV rides
    the collective plane continues the stream byte-identically, with
    the payload never moving over tcp and zero leaks on either side."""
    calls = {"n": 0}
    real = TcpBackend.send_blocks

    async def spy(*a, **kw):
        calls["n"] += 1
        return await real(*a, **kw)

    monkeypatch.setattr(TcpBackend, "send_blocks", spy)
    out = _drive_ici_migration()
    assert out["summary"]["migrated"] == 1
    assert out["summary"]["failed"] == 0
    assert (out["toks"], out["finish"]) == out["want"]
    assert out["dst_scattered"], "no KV reached the peer's cache"
    assert out["sent"] >= 1, "no collective ever entered"
    assert calls["n"] == 0, "payload rode tcp on the ici backend"
    assert out["src_used"] == 0 and out["dst_used"] == 0
    assert out["opens"] and out["opens"][-1]["backend"] == "ici"
    # unified metrics carry the attribution
    assert 'plane="migration"' in out["metrics"]
    assert 'backend="ici"' in out["metrics"]


def test_migration_ici_pre_entry_death_balances_and_fails_over():
    """Mid-stream collective death BEFORE pairing: the receiver's
    reservation is poisoned (freed, nothing installed), the plane is
    balanced and kept, and the controller's failover commits on the
    next attempt — byte-identical."""
    out = _drive_ici_migration(chaos="pre")
    assert out["summary"]["migrated"] == 1
    assert (out["toks"], out["finish"]) == out["want"]
    assert out["balanced"] == 1, "unpaired entry was never balanced"
    assert out["src_ici_alive"], "pre-entry failure must keep the plane"
    assert out["src_used"] == 0 and out["dst_used"] == 0, \
        "poisoned reservation leaked blocks"


def test_migration_ici_post_entry_death_abandons_to_tcp():
    """Mid-stream collective death AFTER entering: the pairing state is
    suspect, the sender abandons the plane, and the retry negotiates
    tcp — byte-identical, zero leaks, with the transfer.open trail
    showing the ici attempt and the tcp failover."""
    out = _drive_ici_migration(chaos="post")
    assert out["summary"]["migrated"] == 1
    assert (out["toks"], out["finish"]) == out["want"]
    assert not out["src_ici_alive"], "entered failure must abandon"
    assert out["src_used"] == 0 and out["dst_used"] == 0
    assert [o["backend"] for o in out["opens"]] == ["ici", "tcp"], \
        f"expected ici attempt then tcp failover, got {out['opens']}"
