"""Collective (ICI/DCN-analog) KV transfer plane.

Two real processes join a jax.distributed world over localhost CPU and
move KV block payloads HBM-analog → HBM-analog through the shared
ppermute program (disagg/ici_transfer.py) — the GPU-free equivalent of
the reference's NIXL RDMA path (examples/llm/utils/nixl.py:59-109).
The in-process tests cover the TCP control frames: ids ride the socket,
and a cancelled request must still enter the collective (deadlock
avoidance) while its payload is dropped.
"""

import asyncio
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from dynamo_tpu.disagg.transfer import KvTransferClient, KvTransferServer

_WORKER = r"""
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.environ["REPO_ROOT"])
from dynamo_tpu.parallel.mesh import MultiHostConfig, initialize_multihost

rank = int(sys.argv[1])
leader = sys.argv[2]
initialize_multihost(MultiHostConfig(
    leader_addr=leader, num_nodes=2, node_rank=rank,
))

import numpy as np
import jax.numpy as jnp
from dynamo_tpu.disagg.ici_transfer import IciKvTransfer

K_SHAPE = (2, 1, 4, 2, 8)   # [L, n, bs, KVH, D]
V_SHAPE = (2, 1, 4, 2, 8)
xfer = IciKvTransfer(
    (K_SHAPE, V_SHAPE), jnp.float32, sender_rank=1, receiver_rank=0,
)
assert xfer.pairs == 2, xfer.pairs  # striping across both device pairs

rng = np.random.default_rng(3)
n = 3  # not a bucket size: exercises pad-to-bucket (4) + slice-back
k_blocks = rng.normal(size=(2, n, 4, 2, 8)).astype(np.float32)
v_blocks = rng.normal(size=(2, n, 4, 2, 8)).astype(np.float32)

if rank == 1:
    xfer.send(k_blocks, v_blocks, seq=41)
    # second transfer re-uses the compiled program
    xfer.send(k_blocks[:, :1] * 2.0, v_blocks[:, :1] * 2.0, seq=42)
    # a balancing entry pairs an orphaned receiver entry with seq -1
    xfer.send_balancing_entry(1)
    print("RANK1_OK", flush=True)
else:
    k, v, seq = xfer.recv(n)
    assert seq == 41, seq
    np.testing.assert_allclose(np.asarray(k), k_blocks, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(v), v_blocks, rtol=1e-6)
    k2, v2, seq2 = xfer.recv(1)
    assert seq2 == 42, seq2
    np.testing.assert_allclose(np.asarray(k2), k_blocks[:, :1] * 2.0, rtol=1e-6)
    k3, v3, seq3 = xfer.recv(1)
    assert seq3 == -1, seq3            # poison payload → caller drops
    assert not np.any(np.asarray(k3))
    print("RANK0_OK", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_collective_transfer():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    leader = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)  # drop the TPU site hook; CPU test
    env["JAX_PLATFORMS"] = "cpu"
    env["REPO_ROOT"] = repo
    # two virtual devices per process: the transfer stripes the payload
    # across both device pairs (the single-pair path is the degenerate
    # case of the same program)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, str(rank), leader],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
            text=True,
        )
        for rank in (0, 1)
    ]
    outs = []
    for rank, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
    assert "RANK0_OK" in outs[0]
    assert "RANK1_OK" in outs[1]


class _StubIci:
    """Stands in for IciKvTransfer.recv on the server side."""

    def __init__(self, seq=0):
        self.calls = []
        self.seq = seq
        self.k = np.ones((2, 2, 4, 2, 8), np.float32)
        self.v = np.full((2, 2, 4, 2, 8), 2.0, np.float32)

    def recv(self, nblocks):
        self.calls.append(nblocks)
        return self.k[:, :nblocks], self.v[:, :nblocks], self.seq


async def test_ici_header_scatters_via_collective():
    ici = _StubIci(seq=9)
    scattered = []
    server = KvTransferServer(
        scatter=lambda rid, ids, k, v: scattered.append((rid, ids, k, v)),
        on_commit=lambda *a: None,
        ici_recv=ici.recv,
    )
    await server.start()
    try:
        client = await KvTransferClient("127.0.0.1", server.port).connect()
        await client.send_ici_blocks("r1", [5, 9], seq=9)
        await client.send_commit("r1", 7)
        await client.close()
    finally:
        await server.close()
    assert ici.calls == [2]
    (rid, ids, k, v), = scattered
    assert rid == "r1" and ids == [5, 9]
    np.testing.assert_array_equal(k, ici.k)
    np.testing.assert_array_equal(v, ici.v)
    assert "ici" in server.descriptor["modes"]


async def test_seq_mismatch_drops_mispaired_payload():
    """A payload whose embedded seq differs from the header's (orphaned
    collective entry pairing with a later send) must never be scattered."""
    ici = _StubIci(seq=3)
    scattered = []
    server = KvTransferServer(
        scatter=lambda rid, ids, k, v: scattered.append(rid),
        on_commit=lambda *a: None,
        ici_recv=ici.recv,
    )
    await server.start()
    try:
        client = await KvTransferClient("127.0.0.1", server.port).connect()
        await client.send_ici_blocks("r1", [5], seq=7)  # header says 7
        await client.send_commit("r1", 0)
        await client.close()
    finally:
        await server.close()
    assert ici.calls == [1]
    assert scattered == []


async def test_cancelled_request_still_enters_collective():
    """Un-authorized ici frames must still call recv (sender is already in
    the collective — skipping would deadlock both workers) but drop data."""
    ici = _StubIci()
    scattered = []
    server = KvTransferServer(
        scatter=lambda rid, ids, k, v: scattered.append(rid),
        on_commit=lambda *a: None,
        authorize=lambda rid, ids: False,
        ici_recv=ici.recv,
    )
    await server.start()
    try:
        client = await KvTransferClient("127.0.0.1", server.port).connect()
        await client.send_ici_blocks("gone", [1])
        await client.send_commit("gone", 0)
        await client.close()
    finally:
        await server.close()
    assert ici.calls == [1]   # entered the collective
    assert scattered == []    # but nothing written


async def test_commit_after_dropped_payload_is_nacked():
    """ADVICE r2 medium-1: a dropped payload (seq mismatch here) must
    poison the request's commit — the decode side would otherwise resume
    over blocks that were never scattered. The sender sees the nack; the
    decode future stays unresolved and local-prefill fallback kicks in."""
    ici = _StubIci(seq=3)
    commits = []
    server = KvTransferServer(
        scatter=lambda rid, ids, k, v: None,
        on_commit=lambda rid, *a: commits.append(rid),
        ici_recv=ici.recv,
    )
    await server.start()
    try:
        client = await KvTransferClient("127.0.0.1", server.port).connect()
        await client.send_ici_blocks("r1", [5], seq=7)  # payload mis-paired
        assert await client.send_commit("r1", 0) is False  # nacked
        # a healthy request on the same connection still commits
        ici.seq = 8
        await client.send_ici_blocks("r2", [6], seq=8)
        assert await client.send_commit("r2", 1) is True
        await client.close()
    finally:
        await server.close()
    assert commits == ["r2"]


async def test_unauthorized_tcp_frame_nacks_commit():
    """The authorize=False drop path marks the request too (TCP frames)."""
    server = KvTransferServer(
        scatter=lambda rid, ids, k, v: None,
        on_commit=lambda *a: pytest.fail("must not commit"),
        authorize=lambda rid, ids: False,
    )
    await server.start()
    try:
        client = await KvTransferClient("127.0.0.1", server.port).connect()
        k = np.zeros((1, 1, 4, 2, 8), np.float32)
        await client.send_blocks("gone", [3], k, k)
        assert await client.send_commit("gone", 0) is False
        await client.close()
    finally:
        await server.close()


async def test_ici_recv_timeout_abandons_plane():
    """ADVICE r2 medium-2: a sender lost after the header must not strand
    the handler forever — the bounded recv times out, the plane is
    abandoned receiver-side, and the request's commit is nacked."""

    class _HangIci:
        def recv(self, nblocks):
            # long enough to trip the 0.3 s bound, short enough that the
            # stranded non-daemon executor thread doesn't hold pytest's
            # interpreter exit hostage
            import time

            time.sleep(5)
            return None, None, 0

    server = KvTransferServer(
        scatter=lambda rid, ids, k, v: pytest.fail("must not scatter"),
        on_commit=lambda *a: pytest.fail("must not commit"),
        ici_recv=_HangIci().recv,
        ici_recv_timeout_s=0.3,
    )
    await server.start()
    try:
        client = await KvTransferClient("127.0.0.1", server.port).connect()
        await client.send_ici_blocks("r1", [5], seq=1)
        assert await client.send_commit("r1", 0) is False  # nacked
        assert server.ici_recv is None  # plane abandoned
        assert "ici" not in server.descriptor["modes"]
        await client.close()
    finally:
        await server.close()


_DEATH_WORKER = r"""
import os, sys, threading
import jax
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.environ["REPO_ROOT"])
from dynamo_tpu.parallel.mesh import MultiHostConfig, initialize_multihost

rank = int(sys.argv[1])
leader = sys.argv[2]
initialize_multihost(MultiHostConfig(
    leader_addr=leader, num_nodes=2, node_rank=rank,
))

import numpy as np
import jax.numpy as jnp
from dynamo_tpu.disagg.ici_transfer import IciKvTransfer

K_SHAPE = (2, 1, 4, 2, 8)
xfer = IciKvTransfer(
    (K_SHAPE, K_SHAPE), jnp.float32, sender_rank=1, receiver_rank=0,
)
k = np.ones((2, 1, 4, 2, 8), np.float32)

if rank == 1:
    xfer.send(k, k, seq=7)       # one good pairing proves the plane works
    print("RANK1_DYING", flush=True)
    os._exit(1)                  # peer death BEFORE the second entry
else:
    k1, v1, seq = xfer.recv(1)
    assert seq == 7, seq
    # the sender is now dead; the unpaired recv must not hang this
    # process forever — bound it the way the serving layer does
    # (KvTransferServer.ici_recv_timeout_s) and classify the plane dead
    result = {}
    def attempt():
        try:
            result["r"] = xfer.recv(1)
        except BaseException as e:
            result["e"] = type(e).__name__
    t = threading.Thread(target=attempt, daemon=True)
    t.start()
    t.join(timeout=25.0)
    if t.is_alive():
        print("RANK0_OK survivor-bounded-timeout", flush=True)
        os._exit(0)              # daemon thread still parked in the collective
    if "e" in result:
        print("RANK0_OK survivor-error", result["e"], flush=True)
        os._exit(0)
    print("RANK0_BAD got data from a dead peer", flush=True)
    os._exit(1)
"""


def test_peer_death_mid_collective_bounds_the_survivor():
    """VERDICT r4 item 8: kill one side between paired entries. The
    survivor must classify the plane dead (error or bounded timeout) —
    never hang forever, never fabricate data. Recovery above this layer:
    the server's ici_recv_timeout_s abandons the plane and the request
    falls back to TCP/local (tests in test_disagg.py)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    leader = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["REPO_ROOT"] = repo
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _DEATH_WORKER, str(rank), leader],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
            text=True,
        )
        for rank in (0, 1)
    ]
    try:
        out1, _ = procs[1].communicate(timeout=240)
        out0, _ = procs[0].communicate(timeout=240)
    except subprocess.TimeoutExpired:
        for q in procs:
            q.kill()
        raise
    assert "RANK1_DYING" in out1
    assert procs[1].returncode == 1  # died on purpose
    assert "RANK0_OK" in out0, out0
    assert procs[0].returncode == 0, out0
