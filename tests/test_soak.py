"""Soak: sustained concurrent load with latency injection and worker churn.

Reference analog: lib/runtime/tests/soak.rs (sustained request load over
the runtime) + tests/common/mock.rs latency models. Scaled to CI: a few
hundred requests, injected jitter, one worker killed and one added
mid-run — every request must complete or fail with a *routable* error
(NoInstancesError during the gap), never hang or corrupt another stream.
"""

import asyncio

import pytest

from dynamo_tpu.runtime.client import Client, NoInstancesError
from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.transports.memory import LatencyModel, MemoryHub

REQUESTS = 120
CONCURRENCY = 16


async def worker_handler(payload, ctx):
    # echo tokens with a tiny compute delay so streams interleave
    for tok in str(payload.get("text", "")).split():
        await asyncio.sleep(0)
        yield {"tok": tok}


@pytest.mark.asyncio
async def test_soak_with_latency_and_churn():
    hub = MemoryHub(latency=LatencyModel(constant=0.0005, jitter=0.002))
    drt = DistributedRuntime.in_process(hub)

    ep = drt.namespace("soak").component("w").endpoint("gen")
    serving_a = await ep.serve(worker_handler, instance_id="worker-a")
    serving_b = await ep.serve(worker_handler, instance_id="worker-b")

    client = await Client(ep).start()
    await client.wait_for_instances(2)

    done = {"ok": 0, "no_instances": 0}
    sem = asyncio.Semaphore(CONCURRENCY)

    async def one(i: int) -> None:
        async with sem:
            text = f"req {i} payload {i % 7}"
            try:
                out = [
                    t["tok"]
                    async for t in client.generate(Context({"text": text}))
                ]
            except NoInstancesError:
                done["no_instances"] += 1
                return
            assert out == text.split(), f"stream {i} corrupted: {out}"
            done["ok"] += 1

    async def churn() -> None:
        # kill one worker a third of the way in, add a fresh one later
        await asyncio.sleep(0.3)
        await serving_a.stop()
        await asyncio.sleep(0.3)
        await ep.serve(worker_handler, instance_id="worker-c")

    churn_task = asyncio.create_task(churn())
    await asyncio.gather(*(one(i) for i in range(REQUESTS)))
    await churn_task

    assert done["ok"] + done["no_instances"] == REQUESTS
    # the surviving worker keeps serving throughout, so the overwhelming
    # majority must succeed (NoInstances only in the watch-update window)
    assert done["ok"] >= REQUESTS * 0.9, done

    # scheduler-ish fairness proxy: after churn the graph still serves
    out = [t["tok"] async for t in client.generate(Context({"text": "final check"}))]
    assert out == ["final", "check"]
    await drt.close()


@pytest.mark.asyncio
async def test_soak_work_queue_backpressure():
    """Work-queue soak: many producers, few consumers, visibility
    redelivery — every job is processed exactly once after acks."""
    hub = MemoryHub(latency=LatencyModel(constant=0.0002, jitter=0.001))
    drt = DistributedRuntime.in_process(hub)
    m = drt.messaging

    jobs = 60
    processed = []

    async def producer():
        for i in range(jobs):
            await m.queue_push("soakq", str(i).encode())

    async def consumer(stop):
        while not stop.is_set():
            item = await m.queue_pop("soakq", timeout=0.2, visibility=5.0)
            if item is None:
                continue
            processed.append(int(item.payload))
            item.ack()

    stop = asyncio.Event()
    consumers = [asyncio.create_task(consumer(stop)) for _ in range(3)]
    await producer()
    while len(processed) < jobs:
        await asyncio.sleep(0.05)
    stop.set()
    await asyncio.gather(*consumers)
    assert sorted(processed) == list(range(jobs))
    assert await m.queue_depth("soakq") == 0
    await drt.close()
