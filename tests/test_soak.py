"""Soak: sustained concurrent load with latency injection and worker churn.

Reference analog: lib/runtime/tests/soak.rs (sustained request load over
the runtime) + tests/common/mock.rs latency models. Scaled to CI: a few
hundred requests, injected jitter, one worker killed and one added
mid-run — every request must complete or fail with a *routable* error
(NoInstancesError during the gap), never hang or corrupt another stream.
"""

import asyncio

import pytest

from dynamo_tpu.runtime.client import Client, NoInstancesError
from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.transports.memory import LatencyModel, MemoryHub

REQUESTS = 120
CONCURRENCY = 16


async def worker_handler(payload, ctx):
    # echo tokens with a tiny compute delay so streams interleave
    for tok in str(payload.get("text", "")).split():
        await asyncio.sleep(0)
        yield {"tok": tok}


@pytest.mark.asyncio
async def test_soak_with_latency_and_churn():
    hub = MemoryHub(latency=LatencyModel(constant=0.0005, jitter=0.002))
    drt = DistributedRuntime.in_process(hub)

    ep = drt.namespace("soak").component("w").endpoint("gen")
    serving_a = await ep.serve(worker_handler, instance_id="worker-a")
    serving_b = await ep.serve(worker_handler, instance_id="worker-b")

    client = await Client(ep).start()
    await client.wait_for_instances(2)

    done = {"ok": 0, "no_instances": 0}
    sem = asyncio.Semaphore(CONCURRENCY)

    async def one(i: int) -> None:
        async with sem:
            text = f"req {i} payload {i % 7}"
            try:
                out = [
                    t["tok"]
                    async for t in client.generate(Context({"text": text}))
                ]
            except NoInstancesError:
                done["no_instances"] += 1
                return
            assert out == text.split(), f"stream {i} corrupted: {out}"
            done["ok"] += 1

    async def churn() -> None:
        # kill one worker a third of the way in, add a fresh one later
        await asyncio.sleep(0.3)
        await serving_a.stop()
        await asyncio.sleep(0.3)
        await ep.serve(worker_handler, instance_id="worker-c")

    churn_task = asyncio.create_task(churn())
    await asyncio.gather(*(one(i) for i in range(REQUESTS)))
    await churn_task

    assert done["ok"] + done["no_instances"] == REQUESTS
    # the surviving worker keeps serving throughout, so the overwhelming
    # majority must succeed (NoInstances only in the watch-update window)
    assert done["ok"] >= REQUESTS * 0.9, done

    # scheduler-ish fairness proxy: after churn the graph still serves
    out = [t["tok"] async for t in client.generate(Context({"text": "final check"}))]
    assert out == ["final", "check"]
    await drt.close()


@pytest.mark.asyncio
async def test_soak_work_queue_backpressure():
    """Work-queue soak: many producers, few consumers, visibility
    redelivery — every job is processed exactly once after acks."""
    hub = MemoryHub(latency=LatencyModel(constant=0.0002, jitter=0.001))
    drt = DistributedRuntime.in_process(hub)
    m = drt.messaging

    jobs = 60
    processed = []

    async def producer():
        for i in range(jobs):
            await m.queue_push("soakq", str(i).encode())

    async def consumer(stop):
        while not stop.is_set():
            item = await m.queue_pop("soakq", timeout=0.2, visibility=5.0)
            if item is None:
                continue
            processed.append(int(item.payload))
            item.ack()

    stop = asyncio.Event()
    consumers = [asyncio.create_task(consumer(stop)) for _ in range(3)]
    await producer()
    while len(processed) < jobs:
        await asyncio.sleep(0.05)
    stop.set()
    await asyncio.gather(*consumers)
    assert sorted(processed) == list(range(jobs))
    assert await m.queue_depth("soakq") == 0
    await drt.close()


@pytest.mark.asyncio
@pytest.mark.slow
async def test_prompt_burst_ttft_bounded_by_batched_prefill():
    """16 concurrent prompts against the real JAX engine: batched prefill
    (max_prefill_batch=4) must cut prefill steps ~4x vs serial and keep
    p95 TTFT bounded (VERDICT r2 weak-4: serial prefill queued TTFT
    linearly under bursts)."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.engine.config import EngineConfig, ModelConfig
    from dynamo_tpu.engine.serving import JaxServingEngine
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.models import llama
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest, SamplingOptions, StopConditions,
    )

    BURST = 16
    mcfg = ModelConfig(
        vocab_size=256, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=8, attention_impl="xla",
    )
    params = llama.init_params(mcfg, jax.random.PRNGKey(0), jnp.float32)
    mdc = ModelDeploymentCard(display_name="t", slug="t", model_path=None)
    rng = np.random.default_rng(7)
    prompts = [
        [1] + rng.integers(2, 256, size=12).tolist() for _ in range(BURST)
    ]

    async def run_burst(max_prefill_batch):
        econfig = EngineConfig(
            model=mcfg, max_batch_size=BURST, max_model_len=64,
            kv_block_size=8, num_kv_blocks=BURST * 8, dtype="float32",
            prefill_buckets=[16], enable_prefix_caching=False,
            max_prefill_batch=max_prefill_batch,
        )
        engine = await JaxServingEngine.create(
            mdc, engine_config=econfig, params=params, warmup=False
        )
        t0 = time.monotonic()
        ttft = [None] * BURST
        outs = [[] for _ in range(BURST)]

        async def one(i):
            req = PreprocessedRequest(
                token_ids=prompts[i],
                stop_conditions=StopConditions(max_tokens=4, ignore_eos=True),
                sampling_options=SamplingOptions(temperature=0.0),
            )
            async for out in engine.generate(Context(req)):
                if out.get("token_ids") and ttft[i] is None:
                    ttft[i] = time.monotonic() - t0
                outs[i].extend(out.get("token_ids") or [])

        await asyncio.gather(*(one(i) for i in range(BURST)))
        steps = engine.scheduler.steps
        await engine.close()
        return ttft, outs, steps

    ttft_b, outs_b, steps_b = await run_burst(4)
    ttft_s, outs_s, steps_s = await run_burst(1)

    # greedy outputs identical regardless of prefill batching
    assert outs_b == outs_s
    # ~4x fewer steps: 16 serial prefills become 4 batched ones (decode
    # steps are identical between runs)
    assert steps_s - steps_b >= 9, (steps_s, steps_b)
    assert all(t is not None for t in ttft_b)
    p95_b = sorted(ttft_b)[int(0.95 * (BURST - 1))]
    # generous absolute bound: the whole burst's first tokens arrive
    # promptly (serial prefill queued them linearly)
    assert p95_b < 30.0, p95_b


@pytest.mark.asyncio
async def test_soak_engine_mixed_guided_traffic():
    """Engine-level soak: concurrent guided-JSON, guided-choice, plain
    sampled, and mid-stream-cancelled requests share one scheduler.
    Every stream must terminate with a coherent finish (or clean
    cancellation), every finished guided-JSON stream must parse, and
    the engine must stay serviceable afterwards."""
    import json as _json

    import jax

    from dynamo_tpu.engine.config import EngineConfig, ModelConfig
    from dynamo_tpu.engine.serving import JaxServingEngine
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.models import llama
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.engine import AsyncEngineContext

    CFG = ModelConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=8, attention_impl="xla",
    )
    econfig = EngineConfig(
        model=CFG, max_batch_size=4, max_model_len=64, kv_block_size=8,
        num_kv_blocks=48, dtype="float32", prefill_buckets=[16],
        allow_random_weights=True,
    )
    mdc = ModelDeploymentCard(display_name="t", slug="t")
    params = llama.init_params(CFG, jax.random.PRNGKey(0), jax.numpy.float32)
    engine = await JaxServingEngine.create(
        mdc, engine_config=econfig, params=params, warmup=False)
    # synthetic piece table (see test_guided.py PIECES rationale)
    pieces = [None] * 128
    for i, sym in enumerate(
            ['{', '}', '[', ']', '"', ':', ',', ' ', '-', '0', '1', '7',
             'a', 'b', 'true', 'null', '{"', '":', '", "', '2.5']):
        pieces[i + 2] = sym
    engine._pieces = pieces
    engine._model_path = "<injected>"

    async def one(i: int):
        kind = i % 4
        so = SamplingOptions(temperature=0.8, seed=i)
        if kind == 0:
            so = SamplingOptions(temperature=0.0,
                                 guided_json={"type": "json_object"})
        elif kind == 1:
            so = SamplingOptions(temperature=1.2, seed=i,
                                 guided_choice_token_ids=[[5, 9], [7]])
        req = PreprocessedRequest(
            token_ids=[1 + (i % 7), 17, 43],
            stop_conditions=StopConditions(max_tokens=12, ignore_eos=True),
            sampling_options=so,
        )
        ctx = AsyncEngineContext(f"soak-{i}")
        toks, finish = [], None
        n = 0
        async for out in engine.generate(Context(req, ctx)):
            toks.extend(out["token_ids"])
            if out.get("finish_reason"):
                finish = out["finish_reason"]
            n += 1
            if kind == 3 and n == 2:
                ctx.stop_generating()  # mid-stream cancellation
        if kind == 0 and finish == "stop":
            text = "".join(pieces[t] for t in toks)
            assert isinstance(_json.loads(text), dict), text
        if kind == 1 and finish == "stop":
            assert toks in ([5, 9], [7])
        if kind != 3:
            assert finish in ("stop", "length"), (kind, finish)
        return finish

    try:
        for wave in range(4):
            results = await asyncio.gather(
                *[one(wave * 12 + j) for j in range(12)])
            assert len(results) == 12
        # still serviceable after the soak
        final = await one(1000)  # kind 0: guided json
        assert final in ("stop", "length")
    finally:
        await engine.close()
