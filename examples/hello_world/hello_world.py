"""Minimal SDK pipeline: Frontend -> Middle -> Backend.

Parity example with the reference's hello_world (reference:
examples/hello_world/hello_world.py — a three-service SDK graph that
upper-cases and decorates a prompt, no model involved). Serve it:

    python -m dynamo_tpu.runtime.transports.dynstore --port 4871 &
    python -m dynamo_tpu.sdk.worker examples.hello_world.hello_world:Frontend \
        --service Backend --store-port 4871 &
    ... (or GraphSupervisor to spawn all three)
"""

from dynamo_tpu.sdk import depends, dynamo_endpoint, service


@service(dynamo={"namespace": "hello"})
class Backend:
    @dynamo_endpoint
    async def generate(self, request):
        for word in request["text"].split(","):
            yield {"text": f"back-{word.strip()}"}


@service(dynamo={"namespace": "hello"})
class Middle:
    backend = depends(Backend)

    @dynamo_endpoint
    async def generate(self, request):
        async for item in self.backend.generate(request):
            yield {"text": f"mid-{item['text']}"}


@service(dynamo={"namespace": "hello"})
class Frontend:
    middle = depends(Middle)

    @dynamo_endpoint
    async def generate(self, request):
        async for item in self.middle.generate(request):
            yield {"text": f"front-{item['text']}"}


Frontend.link(Middle).link(Backend)
