"""Disaggregated prefill/decode: decode Workers offload long prefills to
PrefillWorkers over the namespace queue (reference:
examples/llm/graphs/disagg.py)."""

from ..components import Frontend, PrefillWorker, Processor, Worker

Frontend.link(Processor).link(Worker).link(PrefillWorker)
