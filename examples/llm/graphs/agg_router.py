"""Aggregated serving with KV-aware routing: Frontend -> Processor ->
Router -> Worker (reference: examples/llm/graphs/agg_router.py)."""

from ..components import Frontend, Processor, Router, Worker

Frontend.link(Processor).link(Router).link(Worker)
