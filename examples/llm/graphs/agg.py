"""Aggregated serving: Frontend -> Processor -> Worker (reference:
examples/llm/graphs/agg.py)."""

from ..components import Frontend, Processor, Worker

Frontend.link(Processor).link(Worker)
