"""Disaggregated serving + KV-aware routing — the flagship graph
(reference: examples/llm/graphs/disagg_router.py)."""

from ..components import Frontend, PrefillWorker, Processor, Router, Worker

Frontend.link(Processor).link(Router).link(Worker).link(PrefillWorker)
