"""The canonical LLM serving graph, SDK edition.

Parity with the reference's flagship example (reference: examples/llm/
components/{frontend,processor,kv_router,worker,prefill_worker}.py and
graphs/{agg,agg_router,disagg,disagg_router}.py):

- ``Frontend``   — OpenAI HTTP server + model watcher (reference launches
  the Rust http binary; here the native HTTP service starts in-process).
- ``Processor``  — tokenize/detokenize, route to workers (round-robin or
  via the Router service's KV-aware decision), stream deltas back.
- ``Router``     — KV-aware scheduling service: token ids in, chosen
  worker instance out (reference components/kv_router.py).
- ``Worker``     — token-level engine worker (echo engine by default so
  the graph runs on any machine; ``engine: jax`` + ``model-path`` serves
  a real model) publishing KV events + ForwardPassMetrics.
- ``PrefillWorker`` — consumes the namespace prefill queue for
  disaggregated serving.

Each service reads its options from ServiceConfig (configs/*.yaml).
"""

from __future__ import annotations

import asyncio
import uuid
from typing import Any, AsyncIterator, Optional

from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.sdk import async_on_start, depends, dynamo_endpoint, service

NAMESPACE = "public"


def _opt(obj, key: str, default=None):
    return obj.service_config.get(key, default)


# --------------------------------------------------------------------------


@service(dynamo={"namespace": NAMESPACE})
class Worker:
    """Token-level engine worker (reference: components/worker.py)."""

    @async_on_start
    async def setup(self):
        from dynamo_tpu.cli.run import build_core_engine, load_mdc
        from dynamo_tpu.kv_router.publisher import KvEventPublisher, KvMetricsPublisher

        flags = _WorkerFlags(self.service_config)
        _maybe_join_world(flags)
        self.instance_id = f"w-{uuid.uuid4().hex[:12]}"
        comp = self.drt.namespace(NAMESPACE).component("Worker")
        self.publisher = KvEventPublisher(comp, self.instance_id)
        self.publisher.start()
        mdc = load_mdc(flags) if flags.model_path else None
        self.engine = await build_core_engine(
            _opt(self, "engine", "echo_core"), flags, mdc,
            events=self.publisher.as_sink(), drt=self.drt,
        )
        metrics_fn = getattr(self.engine, "metrics", dict)
        self.stats_handler = KvMetricsPublisher(metrics_fn).stats_handler

    @dynamo_endpoint
    async def generate(self, request, ctx) -> AsyncIterator[dict]:
        async for out in self.engine.generate(Context(request, ctx)):
            yield out


class _WorkerFlags:
    """service_config dict → the flags namespace cli.run helpers expect."""

    def __init__(self, cfg: dict):
        self.model_path = cfg.get("model-path")
        self.model_name = cfg.get("model-name")
        self.kv_block_size = int(cfg.get("kv-block-size", 16))
        self.max_batch_size = int(cfg.get("max-batch-size", 8))
        self.max_model_len = cfg.get("max-model-len")
        self.tensor_parallel_size = int(cfg.get("tensor-parallel-size", 1))
        self.expert_parallel_size = int(cfg.get("expert-parallel-size", 1))
        self.data_parallel_size = int(cfg.get("data-parallel-size", 1))
        self.host_kv_blocks = int(cfg.get("host-kv-blocks", 0))
        self.extra_engine_args = cfg.get("extra-engine-args")
        self.remote_prefill = bool(cfg.get("remote-prefill", False))
        self.max_local_prefill_length = int(cfg.get("max-local-prefill-length", 512))
        self.max_prefill_queue_size = int(cfg.get("max-prefill-queue-size", 16))
        self.namespace = NAMESPACE
        self.advertise_host = cfg.get("advertise-host", "127.0.0.1")
        # multi-host world + collective KV transfer plane (docs/multihost.md,
        # docs/disagg_serving.md) — same keys/defaults as cli.run's parser
        self.num_nodes = int(cfg.get("num-nodes", 1))
        self.node_rank = int(cfg.get("node-rank", 0))
        self.leader_addr = cfg.get("leader-addr", "")
        self.kv_transfer = cfg.get("kv-transfer", "tcp")
        self.ici_sender_rank = int(cfg.get("ici-sender-rank", 1))
        self.ici_receiver_rank = int(cfg.get("ici-receiver-rank", 0))
        if self.max_model_len is not None:
            self.max_model_len = int(self.max_model_len)


def _maybe_join_world(flags) -> None:
    """num-nodes > 1 → join the jax.distributed world BEFORE the first
    backend touch (supervisor mode runs each service in its own process;
    in-process test graphs must not set num-nodes)."""
    if getattr(flags, "num_nodes", 1) > 1:
        from dynamo_tpu.parallel.mesh import MultiHostConfig, initialize_multihost

        initialize_multihost(MultiHostConfig(
            leader_addr=flags.leader_addr,
            num_nodes=flags.num_nodes,
            node_rank=flags.node_rank,
        ))


# --------------------------------------------------------------------------


@service(dynamo={"namespace": NAMESPACE})
class Router:
    """KV-aware worker selection as a service (reference:
    components/kv_router.py + components/router binary)."""

    @async_on_start
    async def setup(self):
        from dynamo_tpu.kv_router.router import KvRouter
        from dynamo_tpu.runtime.client import Client

        block_size = int(_opt(self, "block-size", 16))
        comp = self.drt.namespace(NAMESPACE).component("Worker")
        self.router = await KvRouter(
            comp, Client(comp.endpoint("generate")), block_size=block_size
        ).start()

    @dynamo_endpoint
    async def generate(self, request) -> AsyncIterator[dict]:
        decision = await self.router.schedule(request["token_ids"])
        yield {
            "worker_id": decision.worker_id,
            "prefix_hit_blocks": decision.matched_blocks,
        }


class _RemoteRoutedClient(AsyncEngine):
    """Processor-side client: ask the Router service for a worker, then
    direct-route the preprocessed request to it."""

    def __init__(self, worker_client, router_call):
        self.worker_client = worker_client
        self.router_call = router_call

    async def generate(self, request: Context[Any]) -> AsyncIterator[Any]:
        payload = request.payload
        token_ids = (
            payload.token_ids if hasattr(payload, "token_ids")
            else payload.get("token_ids", [])
        )
        try:
            async for decision in self.router_call({"token_ids": list(token_ids)}):
                request.baggage["instance_id"] = decision["worker_id"]
                break
        except Exception:
            pass  # router down → fall back to the client's own routing
        async for item in self.worker_client.generate(request):
            yield item


@service(dynamo={"namespace": NAMESPACE})
class Processor:
    """OpenAI <-> token translation + routing (reference:
    components/processor.py).

    The Router service is NOT a declared dependency — agg graphs run
    without one; ``router-mode: kv`` builds a client to it lazily (the
    router graphs link it in so the supervisor spawns it)."""

    @async_on_start
    async def setup(self):
        from dynamo_tpu.http.service import register_model
        from dynamo_tpu.llm.backend import Backend
        from dynamo_tpu.llm.model_card import ModelDeploymentCard
        from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
        from dynamo_tpu.llm.tokenizer import HFTokenizer
        from dynamo_tpu.runtime.client import Client, RouterMode
        from dynamo_tpu.runtime.pipeline import build_pipeline

        model_path = _opt(self, "model-path")
        if model_path is None:
            raise ValueError("Processor requires model-path in its config")
        mdc = ModelDeploymentCard.from_local_path(model_path)
        tokenizer = HFTokenizer.from_pretrained_dir(model_path)

        comp = self.drt.namespace(NAMESPACE).component("Worker")
        mode = _opt(self, "router-mode", "round_robin")
        client = Client(
            comp.endpoint("generate"),
            RouterMode.ROUND_ROBIN if mode == "kv" else RouterMode(mode),
        )
        await client.start()
        engine_tail: AsyncEngine = client
        if mode == "kv":
            from dynamo_tpu.sdk import DynamoClient

            router = await DynamoClient(Router, self.drt).start()
            engine_tail = _RemoteRoutedClient(client, router.generate)
        self.engine = build_pipeline(
            [OpenAIPreprocessor(mdc, tokenizer), Backend(tokenizer)], engine_tail
        )
        name = _opt(self, "model-name", mdc.display_name)
        await register_model(
            self.drt, NAMESPACE, name, f"dyn://{NAMESPACE}.Processor.chat",
            model_type="both",
        )

    @dynamo_endpoint
    async def chat(self, request, ctx) -> AsyncIterator[dict]:
        from dynamo_tpu.protocols.openai import ChatCompletionRequest, CompletionRequest

        cls = ChatCompletionRequest if "messages" in request else CompletionRequest
        async for chunk in self.engine.generate(Context(cls.model_validate(request), ctx)):
            yield chunk if isinstance(chunk, dict) else chunk.model_dump(exclude_none=True)


# --------------------------------------------------------------------------


@service(dynamo={"namespace": NAMESPACE})
class Frontend:
    """OpenAI HTTP frontend + discovery-plane model watcher (reference:
    components/frontend.py + components/http binary)."""

    processor = depends(Processor)

    @async_on_start
    async def setup(self):
        from dynamo_tpu.http.service import HttpService, ModelManager, ModelWatcher
        from dynamo_tpu.runtime.client import RouterMode

        manager = ModelManager()
        self.http = HttpService(
            manager,
            _opt(self, "http-host", "0.0.0.0"),
            int(_opt(self, "http-port", 8080)),
        )
        self.watcher = ModelWatcher(
            self.drt, manager, NAMESPACE, RouterMode.ROUND_ROBIN
        )
        await self.watcher.start()
        await self.http.start()


# --------------------------------------------------------------------------


@service(dynamo={"namespace": NAMESPACE})
class PrefillWorker:
    """Dedicated prefill worker consuming the namespace prefill queue
    (reference: components/prefill_worker.py)."""

    @async_on_start
    async def setup(self):
        from dynamo_tpu.cli.run import _make_ici, load_mdc
        from dynamo_tpu.disagg import PrefillWorker as PrefillLoop
        from dynamo_tpu.engine.model_runner import ModelRunner
        from dynamo_tpu.engine.serving import engine_config_from_mdc

        flags = _WorkerFlags(self.service_config)
        _maybe_join_world(flags)
        if flags.model_path is None:
            raise ValueError("PrefillWorker requires model-path in its config")
        mdc = load_mdc(flags)
        engine_config = engine_config_from_mdc(mdc, flags)
        loop = asyncio.get_running_loop()
        runner = await loop.run_in_executor(
            None, lambda: ModelRunner(engine_config, model_dir=mdc.model_path)
        )
        self.worker = PrefillLoop(
            self.drt, runner, engine_config, namespace=NAMESPACE,
            ici=_make_ici(flags, runner),
        )
        self._task = self.drt.runtime.spawn(self.worker.run())
