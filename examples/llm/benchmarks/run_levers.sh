#!/bin/bash
# Sequential lever measurement on a LIVE TPU chip, encoding the
# compile-relay discipline learned in rounds 2 and 4: every attempt goes
# through bench.py (which banks the known-safe XLA number before any
# Pallas compile, probes tiny shapes in a child first, and bounds every
# attempt with a hard timeout), attempts run strictly one at a time, and
# an early wedge aborts the rest instead of queueing compiles behind it.
#
# Usage: ./run_levers.sh [out.jsonl]   (from the repo root's env)
set -u
cd "$(dirname "$0")/../../.."
OUT="${1:-examples/llm/benchmarks/results/levers_$(date -u +%Y%m%d_%H%M).jsonl}"
mkdir -p "$(dirname "$OUT")"

run() {
    local label="$1"; shift
    echo "=== $label ===" | tee -a "$OUT.log"
    # env pairs come as VAR=VAL args
    env "$@" python bench.py > /tmp/lever_out.$$ 2>>"$OUT.log"
    local rc=$?
    # preserve the banked attempt lines in BOTH outcomes — a crash after
    # the XLA bank must not erase the partial measurements
    grep '^attempt\[' /tmp/lever_out.$$ >> "$OUT.log" || true
    if [ $rc -eq 0 ]; then
        tail -1 /tmp/lever_out.$$ | sed "s/^/{\"label\": \"$label\", \"result\": /; s/$/}/" >> "$OUT"
        tail -1 /tmp/lever_out.$$
    else
        cat /tmp/lever_out.$$ >> "$OUT.log"
        echo "{\"label\": \"$label\", \"error\": \"bench rc $rc\"}" >> "$OUT"
        # a crashed bench is at least as abort-worthy as a zero result:
        # never queue more compiles behind a possibly-wedged relay
        echo "bench crashed (rc $rc) on '$label'; stopping the matrix" | tee -a "$OUT.log"
        rm -f /tmp/lever_out.$$
        exit 1
    fi
    # every live attempt failed (bench.py now reports the BANKED number
    # with "banked": true instead of 0.0) → the relay died mid-matrix:
    # stop queueing compiles behind it
    if tail -1 "$OUT" | grep -Eq '"banked": true|"value": 0.0'; then
        echo "relay appears wedged after '$label'; stopping the matrix" | tee -a "$OUT.log"
        exit 1
    fi
    rm -f /tmp/lever_out.$$
}

# Order: cheapest/safest first; each bench.py internally banks XLA
# before Pallas. BENCH_TOTAL_BUDGET_S bounds each lever's spend.
run "bf16-baseline+pallas"  BENCH_TOTAL_BUDGET_S=1200
run "int8-weights"          BENCH_QUANT=int8 BENCH_TOTAL_BUDGET_S=900
run "fp8-kv"                BENCH_KV=fp8 BENCH_TOTAL_BUDGET_S=900
run "int8+fp8kv"            BENCH_QUANT=int8 BENCH_KV=fp8 BENCH_TOTAL_BUDGET_S=900
echo "lever matrix complete: $OUT"
