"""Real-chip serving sweep: flagship-shape engine behind the OpenAI frontend.

Stands up ``in=http out=jax`` with the flagship Llama-3.2-1B-class config
(random-init weights — this measures serving performance, not model
quality) and drives ``loadgen.py`` concurrency levels against it,
mirroring the reference's perf.sh methodology (reference:
examples/llm/benchmarks/perf.sh:18-54 — genai-perf concurrency sweep at
fixed ISL/OSL). Writes one results JSON.

    python examples/llm/benchmarks/serve_sweep.py \
        --out examples/llm/benchmarks/results/serving_tpu_r04.json

The model dir is synthesized on the fly: flagship config.json + the test
tokenizer (512-entry BPE). Sampled ids outside the tokenizer's range
decode to empty strings, which is fine for timing: every generated token
still crosses the full scheduler/detokenizer/SSE path.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))


def make_flagship_dir(tmp: str, smoke: bool = False) -> str:
    from fixtures import make_model_dir
    from __graft_entry__ import FLAGSHIP

    dims = dict(FLAGSHIP)
    if smoke:  # tiny dims: harness logic check on CPU, not a measurement
        dims.update(hidden_size=64, intermediate_size=128,
                    num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16)
        dims.pop("vocab_size")  # tokenizer-sized vocab is fine for smoke
    overrides = {
        "hidden_size": dims["hidden_size"],
        "intermediate_size": dims["intermediate_size"],
        "num_hidden_layers": dims["num_layers"],
        "num_attention_heads": dims["num_heads"],
        "num_key_value_heads": dims["num_kv_heads"],
        "head_dim": dims["head_dim"],
        "rope_theta": dims["rope_theta"],
    }
    if "vocab_size" in dims:
        overrides["vocab_size"] = dims["vocab_size"]
    return make_model_dir(tmp, name="flagship-1b", context_length=2048,
                          config_overrides=overrides)


def make_draft_dir(tmp: str, target_dir: str, layers: int,
                   smoke: bool = False) -> str:
    """Same-tokenizer quarter-width draft next to the target: the engine
    requires exact vocab match (engine/serving.build_draft_config)."""
    import json as _json
    import shutil

    from fixtures import make_model_dir
    from __graft_entry__ import FLAGSHIP

    dims = dict(FLAGSHIP)
    if smoke:
        dims.update(hidden_size=64, intermediate_size=128,
                    num_heads=4, num_kv_heads=2, head_dim=16)
    overrides = {
        "hidden_size": max(dims["hidden_size"] // 4, 64),
        "intermediate_size": max(dims["intermediate_size"] // 4, 128),
        "num_hidden_layers": layers,
        "num_attention_heads": max(dims["num_heads"] // 4, 2),
        "num_key_value_heads": max(dims["num_kv_heads"] // 4, 1),
        "head_dim": dims["head_dim"],
        "rope_theta": dims["rope_theta"],
    }
    with open(os.path.join(target_dir, "config.json")) as f:
        overrides["vocab_size"] = _json.load(f)["vocab_size"]
    d = make_model_dir(tmp, name="flagship-draft", context_length=2048,
                       config_overrides=overrides)
    # identical tokenizer files (the two must share a tokenizer)
    for fn in ("tokenizer.json", "tokenizer_config.json"):
        src = os.path.join(target_dir, fn)
        if os.path.exists(src):
            shutil.copy(src, os.path.join(d, fn))
    return d


async def scrape_spec_metrics(url: str) -> dict:
    """Pull speculation counters off the frontend's /metrics gauges."""
    import re

    import aiohttp

    out = {}
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{url}/metrics") as r:
                text = await r.text()
        for key in ("spec_proposed_tokens", "spec_accepted_tokens"):
            # in-process engines expose dynamo_scheduler_*_total counters
            # (telemetry registry); subprocess/BYO engines still surface
            # dict snapshots as dynamo_engine_* callback gauges
            m = re.search(
                rf"^dynamo_scheduler_{key}_total ([0-9.eE+-]+)$", text,
                re.MULTILINE,
            ) or re.search(rf"^dynamo_engine_{key} ([0-9.eE+-]+)$", text,
                           re.MULTILINE)
            if m:
                out[key] = float(m.group(1))
    except Exception:
        pass
    if out.get("spec_proposed_tokens"):
        out["acceptance_rate"] = round(
            out.get("spec_accepted_tokens", 0.0)
            / out["spec_proposed_tokens"], 4)
    return out


async def wait_ready(url: str, timeout_s: float, server) -> None:
    import aiohttp

    deadline = time.monotonic() + timeout_s
    async with aiohttp.ClientSession() as s:
        while time.monotonic() < deadline:
            if server.poll() is not None:
                raise RuntimeError(
                    f"server exited rc={server.returncode} during warmup "
                    "(see its log tail below)")
            try:
                async with s.get(f"{url}/health") as r:
                    if r.status == 200:
                        return
            except Exception:
                pass
            await asyncio.sleep(2.0)
    raise TimeoutError(f"server at {url} not ready in {timeout_s:.0f}s")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", required=True)
    ap.add_argument("--port", type=int, default=8123)
    ap.add_argument("--concurrency", default="1,4,8,16,32")
    ap.add_argument("--requests", type=int, default=48)
    ap.add_argument("--isl", type=int, default=1000)
    ap.add_argument("--osl", type=int, default=150)
    ap.add_argument("--max-batch-size", type=int, default=8)
    ap.add_argument("--multi-step-decode", type=int, default=8)
    ap.add_argument("--quantization", default=None)
    ap.add_argument("--warmup-timeout", type=float, default=1500.0)
    ap.add_argument("--note", default="")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny model on CPU (JAX_PLATFORMS=cpu): harness "
                         "logic check, not a measurement")
    ap.add_argument("--server-arg", action="append", default=[],
                    help="extra flag passed through to cli.run (repeat; "
                         "e.g. --server-arg=--kv-cache-dtype "
                         "--server-arg=fp8) — lets a chip sweep exercise "
                         "any serving lever without editing the harness")
    ap.add_argument("--spec-draft-layers", type=int, default=0,
                    help="synthesize a same-tokenizer draft model with "
                         "this many layers (quarter width) and serve "
                         "with --spec-draft-model: measures draft-model "
                         "speculation end to end, acceptance scraped "
                         "from /metrics (0 = off)")
    ap.add_argument("--spec-draft-tokens", type=int, default=4)
    args = ap.parse_args()

    tmp = tempfile.mkdtemp(prefix="serve_sweep_")
    model_dir = make_flagship_dir(tmp, smoke=args.smoke)
    draft_dir = None
    if args.spec_draft_layers:
        draft_dir = make_draft_dir(
            tmp, model_dir, layers=args.spec_draft_layers, smoke=args.smoke)
    url = f"http://127.0.0.1:{args.port}"

    cmd = [
        sys.executable, "-m", "dynamo_tpu.cli.run",
        "in=http", "out=jax",
        "--model-path", model_dir, "--model-name", "flagship-1b",
        "--allow-random-weights",
        "--http-port", str(args.port),
        "--max-batch-size", str(args.max_batch_size),
        "--max-model-len", "2048",
        "--num-kv-blocks", "2048",
        "--multi-step-decode", str(args.multi_step_decode),
    ]
    if args.quantization:
        cmd += ["--quantization", args.quantization]
    if draft_dir is not None:
        cmd += ["--spec-draft-model", draft_dir,
                "--spec-draft-tokens", str(args.spec_draft_tokens)]
    cmd += args.server_arg
    env = dict(os.environ)
    if args.smoke:
        env["JAX_PLATFORMS"] = "cpu"
    server_log = os.path.join(tmp, "server.log")
    with open(server_log, "w") as lf:
        server = subprocess.Popen(
            cmd, cwd=REPO, stdout=lf, stderr=subprocess.STDOUT, env=env,
            start_new_session=True,
        )
    levels = []

    def write_out(t_ready: float) -> None:
        # re-written after every level: an aborted sweep (loadgen
        # timeout, Ctrl-C) keeps the levels already measured — real-chip
        # time is too scarce to lose an hour of completed levels
        out = {
            "note": args.note or (
                "Serving sweep on ONE real TPU v5e chip (axon relay): "
                "flagship 1B-class llama (random weights), in=http "
                "out=jax, streaming chat completions. Measures the full "
                "stack: HTTP+SSE, preprocessor, continuous batching, "
                "chunked prefill, fused multi-step decode."),
            "config": {
                "model": "llama-1b-class (FLAGSHIP dims)",
                "max_batch_size": args.max_batch_size,
                "multi_step_decode": args.multi_step_decode,
                "quantization": args.quantization,
                "server_args": args.server_arg,  # the lever under test
                "spec_draft_layers": args.spec_draft_layers or None,
                "spec_draft_tokens": (args.spec_draft_tokens
                                      if args.spec_draft_layers else None),
                "isl": args.isl, "osl": args.osl,
            },
            **({"spec": spec_box} if spec_box else {}),
            "sweep_wall_s": round(time.monotonic() - t_ready, 1),
            "levels": levels,
        }
        os.makedirs(os.path.dirname(os.path.abspath(args.out)),
                    exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)

    spec_box: dict = {}
    try:
        asyncio.run(wait_ready(url, args.warmup_timeout, server))
        t_ready = time.monotonic()
        for c in [int(x) for x in args.concurrency.split(",")]:
            try:
                lg = subprocess.run(
                    [sys.executable, "examples/llm/benchmarks/loadgen.py",
                     "--url", url, "--model", "flagship-1b",
                     "--concurrency", str(c),
                     "--requests", str(max(args.requests, 2 * c)),
                     "--isl", str(args.isl), "--osl", str(args.osl)],
                    cwd=REPO, capture_output=True, text=True, timeout=1800,
                )
            except subprocess.TimeoutExpired:
                print(f"loadgen c={c} timed out; keeping completed "
                      "levels", flush=True)
                break
            for line in lg.stdout.splitlines():
                line = line.strip()
                if line.startswith("{"):
                    try:
                        lvl = json.loads(line)
                    except ValueError:
                        continue  # log line that happens to start with '{'
                    levels.append(lvl)
                    print(json.dumps(lvl), flush=True)
            if lg.returncode != 0:
                print(f"loadgen c={c} rc={lg.returncode}: "
                      f"{lg.stderr[-500:]}", flush=True)
            if draft_dir is not None:
                spec_box.update(asyncio.run(scrape_spec_metrics(url)))
            write_out(t_ready)
        write_out(t_ready)
        print(f"wrote {args.out}", flush=True)
    finally:
        try:
            os.killpg(server.pid, signal.SIGTERM)
        except Exception:
            server.terminate()
        try:
            server.wait(timeout=20)
        except Exception:
            try:
                os.killpg(server.pid, signal.SIGKILL)
            except Exception:
                pass
        sys.stdout.write(open(server_log).read()[-2000:])


if __name__ == "__main__":
    main()
