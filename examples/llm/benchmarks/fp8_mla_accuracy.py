"""Is an fp8 (e4m3) MLA latent cache accurate enough to serve?

The engine guards fp8 KV to GQA families (engine/model_runner.py): the
MLA compressed latent doubles as BOTH the key source (through the
absorbed W_uk) and the value (through W_uv), so e4m3 noise passes
through two learned projections instead of landing directly in a
softmax-bounded score. This script puts a number on that intuition the
way the VERDICT asked: same-seed tiny models, caches round-tripped
through e4m3 after prefill, logit deltas + greedy divergence vs the
full-precision cache — GQA (llama) side by side with MLA (deepseek),
plus the rope-half-only variant (quantize k_rope, keep the latent c in
bf16) as the candidate middle ground.

Run (CPU, ~1 min): PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    python examples/llm/benchmarks/fp8_mla_accuracy.py
Results land next to this file as fp8_mla_accuracy.json.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", ".."))
from dynamo_tpu.utils.platform import apply_jax_platform_override  # noqa: E402

apply_jax_platform_override()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from dynamo_tpu.engine.config import ModelConfig  # noqa: E402
from dynamo_tpu.models import deepseek, llama  # noqa: E402

STEPS = 24
B, CTX0 = 2, 33


def _roundtrip(x, which):
    if which == "none":
        return x
    return x.astype(jnp.float8_e4m3fn).astype(x.dtype)


def _run(cfg, arch, quant_fn, forced=None, steps=STEPS):
    """Decode ``steps`` tokens; cache round-trips through e4m3 per
    ``quant_fn`` after every write. ``forced`` [B, steps+1] teacher-
    forces the input tokens so every variant sees IDENTICAL inputs —
    the per-step logit delta then measures cache-quantization noise
    alone, not trajectory divergence. Returns (greedy_tokens [B, T],
    per_step_logits [T, B, V])."""
    params = arch.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    n_blocks, bs = 64, 8
    cache = arch.init_kv_cache(cfg, n_blocks, bs, jnp.float32)
    w = 16
    bt = jnp.asarray(
        np.arange(B * w, dtype=np.int32).reshape(B, w) % n_blocks)
    rng = np.random.default_rng(7)
    prompt = rng.integers(1, cfg.vocab_size, (B, CTX0)).astype(np.int32)

    pos = jnp.tile(jnp.arange(CTX0, dtype=jnp.int32), (B, 1))
    slots = (bt.repeat(bs, axis=1)[:, :CTX0] * bs
             + (jnp.arange(CTX0) % bs)[None, :])
    ctx = jnp.full((B,), CTX0, jnp.int32)
    logits, cache = arch.forward(
        params, cfg, jnp.asarray(prompt), pos, cache, bt, slots, ctx)
    cache = tuple(quant_fn(c, i) for i, c in enumerate(cache))

    toks = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
    greedy = [np.asarray(toks)]
    step_logits = [np.asarray(logits[:, -1])]
    for t in range(steps):
        p = CTX0 + t
        inp = (jnp.asarray(forced[:, t]) if forced is not None else toks)
        step_slots = (bt[:, p // bs] * bs + p % bs)[:, None]
        logits, cache = arch.forward(
            params, cfg, inp[:, None],
            jnp.full((B, 1), p, jnp.int32), cache, bt, step_slots,
            jnp.full((B,), p + 1, jnp.int32))
        cache = tuple(quant_fn(c, i) for i, c in enumerate(cache))
        toks = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
        greedy.append(np.asarray(toks))
        step_logits.append(np.asarray(logits[:, -1]))
    return np.stack(greedy, 1), np.stack(step_logits)


def _compare(cfg, arch, variants):
    base_toks, base_logits = _run(cfg, arch, lambda c, i: c)
    rows = {}
    for name, fn in variants.items():
        # teacher-force the BASELINE's greedy tokens: identical inputs,
        # so logit deltas isolate the cache noise
        toks, logits = _run(cfg, arch, fn, forced=base_toks)
        flips = (toks != base_toks).mean()
        rel = float(np.abs(logits - base_logits).mean()
                    / (np.abs(base_logits).mean() + 1e-9))
        # noise relative to the logit MARGIN that decides the argmax
        top2 = np.sort(base_logits, -1)[..., -2:]
        margin = float((top2[..., 1] - top2[..., 0]).mean())
        noise = float(np.abs(logits - base_logits).max(-1).mean())
        rows[name] = {
            "teacher_forced_argmax_flip_rate": round(float(flips), 4),
            "mean_rel_logit_err": round(rel, 5),
            "mean_max_logit_noise": round(noise, 4),
            "mean_top2_margin": round(margin, 4),
        }
    return rows


def main() -> None:
    gqa = ModelConfig(
        vocab_size=512, hidden_size=128, intermediate_size=256,
        num_layers=4, num_heads=8, num_kv_heads=4, head_dim=16,
        attention_impl="xla",
    )
    mla = ModelConfig(
        vocab_size=512, hidden_size=128, intermediate_size=256,
        num_layers=4, num_heads=8, num_kv_heads=8, head_dim=16,
        kv_lora_rank=32, qk_rope_head_dim=16, qk_nope_head_dim=16,
        v_head_dim=16, attention_impl="xla",
    )
    results = {
        "note": (
            "e4m3 cache round-trip after every write vs full-precision "
            "cache; same seed/weights/prompts. GQA quantizes k+v (the "
            "shipped --kv-cache-dtype fp8 path); MLA variants: full "
            "(latent c + k_rope), rope_only (k_rope quantized, latent "
            "kept), latent_only (latent quantized, k_rope kept)."
        ),
        "steps": STEPS,
        "gqa_llama": _compare(gqa, llama, {
            "fp8_kv": lambda c, i: _roundtrip(c, "q"),
        }),
        "mla_deepseek": _compare(mla, deepseek, {
            "fp8_full": lambda c, i: _roundtrip(c, "q"),
            "fp8_rope_only": lambda c, i: (
                _roundtrip(c, "q") if i == 1 else c),
            "fp8_latent_only": lambda c, i: (
                _roundtrip(c, "q") if i == 0 else c),
        }),
    }
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "results", "fp8_mla_accuracy.json")
    with open(out, "w") as f:
        json.dump(results, f, indent=1)
    print(json.dumps(results, indent=1))


if __name__ == "__main__":
    main()
