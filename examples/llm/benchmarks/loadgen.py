"""OpenAI-frontend load generator: concurrency sweep with TTFT/ITL stats.

Reference analog: examples/llm/benchmarks/perf.sh (reference:
examples/llm/benchmarks/perf.sh:18-54 — genai-perf concurrency sweep
1→256 at ISL 3000 / OSL 150 against the deployed graph). Same
methodology without the external tool: streaming chat requests at a
bounded concurrency, measuring per-request time-to-first-token,
inter-token latency, and end-to-end duration, aggregated per
concurrency level as one JSON line.

    python examples/llm/benchmarks/loadgen.py \
        --url http://127.0.0.1:8080 --model m8b \
        --concurrency 1,4,16,64 --requests 64 --isl 3000 --osl 150

ISL is approximated with a repeated-word prompt unless --prompt-file
provides real text (token-exact ISL needs the server's tokenizer; the
reference's genai-perf synthesizes prompts the same way).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time
from typing import List, Optional

import aiohttp


def percentile(values: List[float], p: float) -> Optional[float]:
    """None (→ JSON null) on empty input: NaN is not valid JSON, and an
    all-errors level is exactly when the output must stay parseable."""
    if not values:
        return None
    xs = sorted(values)
    i = min(len(xs) - 1, int(round(p / 100 * (len(xs) - 1))))
    return xs[i]


def _ms(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(1e3 * v, 1)


class RequestResult:
    __slots__ = ("ok", "ttft", "duration", "itls", "tokens", "error")

    def __init__(self):
        self.ok = False
        self.ttft: Optional[float] = None
        self.duration = 0.0
        self.itls: List[float] = []
        self.tokens = 0
        self.error: Optional[str] = None


async def run_one(
    session: aiohttp.ClientSession, url: str, model: str, prompt: str,
    osl: int,
) -> RequestResult:
    res = RequestResult()
    body = {
        "model": model,
        "messages": [{"role": "user", "content": prompt}],
        "max_tokens": osl,
        "temperature": 0.0,
        "stream": True,
        # count completion tokens server-side (usage on the final chunk)
        "stream_options": {"include_usage": True},
    }
    t0 = time.perf_counter()
    last = t0
    try:
        async with session.post(
            f"{url}/v1/chat/completions", json=body,
            timeout=aiohttp.ClientTimeout(total=600),
        ) as resp:
            if resp.status != 200:
                res.error = f"http {resp.status}: {(await resp.text())[:200]}"
                return res
            async for raw in resp.content:
                line = raw.decode().strip()
                if not line.startswith("data:"):
                    continue
                payload = line[5:].strip()
                if payload == "[DONE]":
                    break
                chunk = json.loads(payload)
                now = time.perf_counter()
                if chunk.get("usage"):
                    res.tokens = chunk["usage"].get("completion_tokens", 0)
                choices = chunk.get("choices") or []
                if choices and (
                    (choices[0].get("delta") or {}).get("content")
                    or choices[0].get("finish_reason")
                ):
                    if res.ttft is None:
                        res.ttft = now - t0
                    else:
                        res.itls.append(now - last)
                    last = now
        res.duration = time.perf_counter() - t0
        res.ok = res.ttft is not None
    except Exception as e:  # noqa: BLE001 — any failure is a data point
        res.error = f"{type(e).__name__}: {e}"
    return res


async def run_level(
    url: str, model: str, prompt: str, osl: int, requests: int,
    concurrency: int,
) -> dict:
    sem = asyncio.Semaphore(concurrency)
    results: List[RequestResult] = []
    t0 = time.perf_counter()

    # the default connector caps at 100 connections — a 256-level sweep
    # would silently measure 100-way concurrency with pool-wait time
    # folded into TTFT
    connector = aiohttp.TCPConnector(limit=max(concurrency, 100))
    async with aiohttp.ClientSession(connector=connector) as session:
        async def one():
            async with sem:
                results.append(
                    await run_one(session, url, model, prompt, osl)
                )

        await asyncio.gather(*(one() for _ in range(requests)))
    wall = time.perf_counter() - t0

    oks = [r for r in results if r.ok]
    ttfts = [r.ttft for r in oks]
    itls = [itl for r in oks for itl in r.itls]
    tokens = sum(r.tokens or len(r.itls) + 1 for r in oks)
    return {
        "concurrency": concurrency,
        "requests": requests,
        "ok": len(oks),
        "errors": len(results) - len(oks),
        "req_per_s": round(len(oks) / wall, 3) if wall else 0.0,
        "output_tok_per_s": round(tokens / wall, 1) if wall else 0.0,
        "ttft_p50_ms": _ms(percentile(ttfts, 50)),
        "ttft_p95_ms": _ms(percentile(ttfts, 95)),
        "itl_p50_ms": _ms(percentile(itls, 50)),
        "itl_p95_ms": _ms(percentile(itls, 95)),
        "duration_s": round(wall, 2),
    }


async def sweep(
    url: str, model: str, prompt: str, osl: int, requests: int,
    levels: List[int],
) -> List[dict]:
    out = []
    for c in levels:
        level = await run_level(url, model, prompt, osl, requests, c)
        print(json.dumps(level), flush=True)
        out.append(level)
    return out


def build_prompt(isl_words: int, prompt_file: Optional[str]) -> str:
    if prompt_file:
        with open(prompt_file) as f:
            return f.read()
    # synthetic prompt ~1 token/word for common tokenizers
    return " ".join(f"word{i % 97}" for i in range(isl_words))


def main() -> None:
    p = argparse.ArgumentParser(description="dynamo-tpu load generator")
    p.add_argument("--url", default="http://127.0.0.1:8080")
    p.add_argument("--model", required=True)
    p.add_argument("--concurrency", default="1,4,16",
                   help="comma-separated sweep levels")
    p.add_argument("--requests", type=int, default=32,
                   help="requests per level")
    p.add_argument("--isl", type=int, default=3000,
                   help="approx input length in words (reference sweep: 3000)")
    p.add_argument("--osl", type=int, default=150,
                   help="output tokens per request (reference sweep: 150)")
    p.add_argument("--prompt-file", default=None)
    args = p.parse_args()

    prompt = build_prompt(args.isl, args.prompt_file)
    levels = [int(x) for x in args.concurrency.split(",") if x]
    asyncio.run(
        sweep(args.url, args.model, prompt, args.osl, args.requests, levels)
    )


if __name__ == "__main__":
    main()
