// Example EXTERNAL engine integrating through the C ABI.
//
// Demonstrates the contract the reference exposes through lib/bindings/c
// (KV event publication from a non-Python engine): a C++ engine embeds
// the dt_* symbols (dynamo_tpu/native/src/capi.cc), publishes
// stored/removed KV-block events as it fills its own cache, and serves
// generation through a tiny C interface that any host (here:
// examples/external_engine/engine.py via ctypes) can call.
//
// Build (the test does this automatically):
//   g++ -O2 -shared -fPIC -I dynamo_tpu/native/src \
//       examples/external_engine/engine.cc dynamo_tpu/native/src/capi.cc \
//       -o ext_engine.so
//
// The engine itself is deliberately trivial — it echoes the prompt —
// because the point is the INTEGRATION surface, not the model: real
// engines swap the body of ext_generate and keep the same dt_* event
// calls. (Echoing forward, not reversed: a reversed chat prompt leads
// with the template's EOS and the backend correctly stops at once.)

#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {
int dt_capi_init(const char* ns, const char* component, const char* worker_id,
                 uint32_t kv_block_size, uint64_t capacity);
int dt_capi_shutdown();
// block hashes are computed ABI-side from the tokens (same rolling
// scheme as the router's indexer); parent_hash chains prefix blocks
int dt_kv_event_publish_stored(uint64_t event_id, const uint32_t* tokens,
                               size_t num_tokens, const uint64_t* parent_hash);
int dt_kv_event_publish_removed(uint64_t event_id, const uint64_t* block_hashes,
                                size_t num_blocks);

int ext_engine_init(const char* worker_id, uint32_t block_size) {
  return dt_capi_init("public", "backend", worker_id, block_size, 4096);
}

int ext_engine_shutdown() { return dt_capi_shutdown(); }

// Generate: reverse the prompt into `out` (toy decode), publishing one
// "stored" KV event per full block of the prompt — exactly what a real
// engine does as prefill KV lands in its cache.
long ext_generate(const uint32_t* prompt, size_t n, uint32_t block_size,
                  uint32_t* out, size_t cap) {
  static uint64_t event_id = 0;
  size_t nblocks = n / block_size;
  if (nblocks > 0) {
    // no parent: each prompt starts a fresh prefix chain
    dt_kv_event_publish_stored(++event_id, prompt, nblocks * block_size,
                               nullptr);
  }
  size_t m = n < cap ? n : cap;
  for (size_t i = 0; i < m; ++i) out[i] = prompt[i];
  return static_cast<long>(m);
}
}
