"""BYO-engine shim hosting the C++ external engine (engine.cc).

Run with:  dynamo-run in=http out=pytok:examples/external_engine/engine.py \
               --model-path <tokenizer dir>

The shim shows the full external-engine integration surface the
reference offers through its C bindings (lib/bindings/c): the engine is
a shared library speaking the dt_* ABI; generation flows through the
pytok contract (PreprocessedRequest in, EngineOutput chunks out), and
the KV events the C++ side publishes are drained with dt_capi_drain —
ready to feed a KVEventPublisher so the KV router prefix-matches onto
this engine like any native one.
"""

import asyncio
import ctypes
import json
import os

_HERE = os.path.dirname(os.path.abspath(__file__))
_LIB = None
_BLOCK_SIZE = 16


def _build_and_load():
    global _LIB
    if _LIB is not None:
        return _LIB
    repo = os.path.dirname(os.path.dirname(_HERE))
    so = os.path.join(_HERE, "ext_engine.so")
    src = os.path.join(_HERE, "engine.cc")
    capi = os.path.join(repo, "dynamo_tpu", "native", "src", "capi.cc")
    if not os.path.exists(so) or os.path.getmtime(so) < max(
        os.path.getmtime(src), os.path.getmtime(capi)
    ):
        import subprocess

        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", src, capi,
             "-o", so],
            check=True,
        )
    lib = ctypes.CDLL(so)
    lib.ext_engine_init.restype = ctypes.c_int
    lib.ext_engine_init.argtypes = [ctypes.c_char_p, ctypes.c_uint32]
    lib.ext_generate.restype = ctypes.c_long
    lib.ext_generate.argtypes = [
        ctypes.POINTER(ctypes.c_uint32), ctypes.c_size_t, ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_uint32), ctypes.c_size_t,
    ]
    lib.dt_capi_drain.restype = ctypes.c_long
    lib.dt_capi_drain.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    _LIB = lib
    return lib


async def initialize(engine_args: dict):
    lib = _build_and_load()
    rc = lib.ext_engine_init(b"ext-worker-0", _BLOCK_SIZE)
    if rc != 0:
        raise RuntimeError(f"ext_engine_init failed rc={rc}")


def drain_kv_events():
    """Pull KV events the C++ engine published (JSON dicts)."""
    lib = _build_and_load()
    out, events = ctypes.create_string_buffer(1 << 16), []
    while True:
        n = lib.dt_capi_drain(out, len(out))
        if n <= 0:
            break
        events.append(json.loads(out.raw[:n].decode()))
    return events


async def generate(request: dict):
    lib = _build_and_load()
    prompt = request.get("token_ids") or []
    max_tokens = (request.get("stop_conditions") or {}).get("max_tokens") or 8
    arr = (ctypes.c_uint32 * max(len(prompt), 1))(*prompt)
    cap = max(int(max_tokens), 1)
    out = (ctypes.c_uint32 * cap)()
    n = await asyncio.get_running_loop().run_in_executor(
        None,
        lambda: lib.ext_generate(arr, len(prompt), _BLOCK_SIZE, out, cap),
    )
    for i in range(n):
        yield {"token_ids": [int(out[i])]}
    yield {"token_ids": [], "finish_reason": "stop"}
